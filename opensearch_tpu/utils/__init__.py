from .breaker import BreakerService, CircuitBreaker, CircuitBreakingException

__all__ = ["BreakerService", "CircuitBreaker", "CircuitBreakingException"]
