"""Search backpressure: per-task resource tracking + cancellation of the
worst offender under node duress, and a hard admission gate.

Reference analogs: `search/backpressure/SearchBackpressureService.java:68`
(tracks task CPU/heap, cancels the most resource-consuming search tasks
when the node is in duress) and `ratelimitting/admissioncontrol/` (rejects
new work outright past a hard limit).

TPU-design notes: the scarce resource here is device time — one chip
serializes kernel launches, so a runaway scan starves neighbors by queue
depth, not by heap. Tasks therefore account wall-clock device seconds
(accumulated between segment programs, the same safe points cancellation
polls) plus the bytes their plans moved to device. Duress = too many
in-flight search tasks; the service then cancels the cancellable task
with the highest device time above the minimum threshold. Deterministic:
callers own the clock (like cluster/failure.py)."""

from __future__ import annotations

import time
from typing import List, Optional


class SearchBackpressureService:
    def __init__(self,
                 max_in_flight: int = 32,        # duress threshold
                 hard_limit: int = 256,          # admission-control reject
                 cancel_min_device_s: float = 1.0,
                 cancellation_ratio: float = 0.1):
        self.max_in_flight = max_in_flight
        self.hard_limit = hard_limit
        self.cancel_min_device_s = cancel_min_device_s
        self.cancellation_ratio = cancellation_ratio
        self.cancellation_count = 0
        self.rejection_count = 0
        self.limit_reached_count = 0
        # serving-scheduler queue-full rejections (serving/scheduler.py):
        # the scheduler's bounded queue is an admission surface too, and
        # its 429s belong in the same backpressure ledger operators watch
        self.scheduler_rejection_count = 0

    def note_queue_rejection(self) -> None:
        """A serving-scheduler enqueue was rejected (queue full -> 429)."""
        self.scheduler_rejection_count += 1

    # -------- admission (reference admissioncontrol) --------

    def admit(self, registry) -> None:
        from .wlm import PressureRejectedException
        if self._in_flight(registry) >= self.hard_limit:
            self.rejection_count += 1
            raise PressureRejectedException(
                f"rejecting search: {self.hard_limit} searches already in "
                f"flight (admission control)")

    # -------- duress monitoring (reference SearchBackpressureService) ----

    def _in_flight(self, registry) -> int:
        return sum(1 for t in registry.list("indices:data/read/search*"))

    def check(self, registry, now: Optional[float] = None) -> List[int]:
        """Cancel the worst offenders when the node is in duress; returns
        the cancelled task ids. Called on search admission and by the
        stats/monitor tick."""
        tasks = [t for t in registry.all()
                 if t.action.startswith("indices:data/read/search")
                 and not t.cancelled and t.cancellable]
        if len(tasks) <= self.max_in_flight:
            return []
        self.limit_reached_count += 1
        # victims: highest device time first, above the floor; cancel at
        # most ceil(ratio * in-flight) per pass so bursts drain gradually
        victims = sorted(
            (t for t in tasks if t.device_seconds >= self.cancel_min_device_s),
            key=lambda t: t.device_seconds, reverse=True)
        budget = max(1, int(len(tasks) * self.cancellation_ratio))
        out: List[int] = []
        for t in victims[:budget]:
            t.cancel("cancelled by search backpressure (resource tracking: "
                     f"{t.device_seconds:.2f}s device time)")
            self.cancellation_count += 1
            out.append(t.id)
        return out

    def stats(self) -> dict:
        return {
            "mode": "enforced",
            "search_task": {
                "cancellation_count": self.cancellation_count,
                "limit_reached_count": self.limit_reached_count,
                "rejection_count": self.rejection_count,
                "scheduler_rejection_count": self.scheduler_rejection_count,
                "cancel_min_device_seconds": self.cancel_min_device_s,
                "max_in_flight": self.max_in_flight,
                "hard_limit": self.hard_limit,
            },
        }
