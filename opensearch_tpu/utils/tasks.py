"""Task management + cooperative cancellation.

Reference `tasks/TaskManager.java` + `tasks/CancellableTask.java`: every
long-running action registers a task; cancellation is cooperative — the
running code polls `ensure_not_cancelled()` at safe points (between segments
in the query phase, between docs in reindex loops). Device programs are
uncancellable once dispatched (like a Lucene segment scorer mid-advance);
the poll granularity is one segment's kernel, which is milliseconds."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class TaskCancelledException(Exception):
    """Reference TaskCancelledException -> HTTP 400 search_phase_execution."""


class Task:
    def __init__(self, task_id: int, action: str, description: str,
                 cancellable: bool = True):
        self.id = task_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        self.start_time = time.time()          # wall clock, display only
        self._start_mono = time.monotonic()    # durations (running time)
        self.cancelled = False
        self.cancel_reason: Optional[str] = None
        # resource tracking (utils/backpressure.py; reference
        # TaskResourceTrackingService): accumulated at segment boundaries
        self.device_seconds = 0.0
        self.mem_bytes = 0
        # cancellation listeners (serving/scheduler.py drops queued
        # entries the moment their task is cancelled, instead of waiting
        # for the next flush assembly to notice)
        self._cancel_listeners: list = []
        self._listener_lock = threading.Lock()
        # live serving introspection (serving/scheduler.py stage marks):
        # queued -> launched -> fetching -> rendering. None = the task
        # never entered the scheduler (direct path) — `info()` then omits
        # the serving block entirely, keeping the legacy shape.
        self.stage: Optional[str] = None
        self._stage_mono: Optional[float] = None
        self._queue_enq_mono: Optional[float] = None
        self.queue_wait_ms: Optional[float] = None
        # flight-recorder timeline carrying this task's event journal
        # (obs/flight_recorder.py); 0 = recorder disabled
        self.timeline_id = 0

    def track(self, device_seconds: float = 0.0, mem_bytes: int = 0) -> None:
        self.device_seconds += device_seconds
        self.mem_bytes += mem_bytes

    def set_stage(self, stage: Optional[str]) -> None:
        """Mark the task's live serving stage (scheduler transitions).
        The first transition OUT of "queued" freezes queue_wait_ms; while
        still queued, `info()` reports the wait so far. Benign-racy by
        design: single writes of plain attributes read by the stats
        thread."""
        now = time.monotonic()
        if stage == "queued":
            self._queue_enq_mono = now
        elif self.stage == "queued" and self._queue_enq_mono is not None \
                and self.queue_wait_ms is None:
            self.queue_wait_ms = (now - self._queue_enq_mono) * 1000.0
        self.stage = stage
        self._stage_mono = now

    def on_cancel(self, callback) -> None:
        """Register `callback(task)` to run when this task is cancelled;
        fires immediately if the task is already cancelled. Listener
        errors never poison the canceller."""
        fire = False
        with self._listener_lock:
            if self.cancelled:
                fire = True
            else:
                self._cancel_listeners.append(callback)
        if fire:
            try:
                callback(self)
            except Exception:       # noqa: BLE001
                pass

    def cancel(self, reason: str = "by user request") -> None:
        if not self.cancellable:
            return
        with self._listener_lock:
            self.cancelled = True
            self.cancel_reason = reason
            listeners, self._cancel_listeners = self._cancel_listeners, []
        for cb in listeners:
            try:
                cb(self)
            except Exception:       # noqa: BLE001
                pass

    def ensure_not_cancelled(self) -> None:
        if self.cancelled:
            raise TaskCancelledException(
                f"task [{self.id}] was cancelled: {self.cancel_reason}")

    def info(self) -> dict:
        out = {"id": self.id, "action": self.action,
               "description": self.description,
               "cancellable": self.cancellable,
               "cancelled": self.cancelled,
               "start_time_in_millis": int(self.start_time * 1000),
               "running_time_in_nanos":
                   int((time.monotonic() - self._start_mono) * 1e9),
               "resource_stats": {"device_time_seconds":
                                  round(self.device_seconds, 6),
                                  "memory_in_bytes": self.mem_bytes}}
        if self.timeline_id:
            out["flight_recorder_timeline"] = self.timeline_id
        stage = self.stage
        if stage is not None:
            now = time.monotonic()
            mark = self._stage_mono
            serving = {"stage": stage,
                       "stage_elapsed_ms":
                           round((now - mark) * 1000.0, 3)
                           if mark is not None else None}
            qw = self.queue_wait_ms
            if qw is None and stage == "queued" \
                    and self._queue_enq_mono is not None:
                qw = (now - self._queue_enq_mono) * 1000.0
            if qw is not None:
                serving["queue_wait_so_far_ms"] = round(qw, 3)
            out["serving"] = serving
        return out


class TaskRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: Dict[int, Task] = {}
        self._next = 0
        self.completed = 0

    def register(self, action: str, description: str = "",
                 cancellable: bool = True) -> Task:
        with self._lock:
            self._next += 1
            t = Task(self._next, action, description, cancellable)
            self._tasks[t.id] = t
            return t

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.id, None)
            self.completed += 1

    def get(self, task_id: int) -> Optional[Task]:
        return self._tasks.get(task_id)

    def cancel(self, task_id: int, reason: str = "by user request") -> bool:
        t = self._tasks.get(task_id)
        if t is None or not t.cancellable:
            return False
        t.cancel(reason)
        return True

    def list(self, actions: Optional[str] = None) -> List[dict]:
        out = [t.info() for t in list(self._tasks.values())]
        if actions:
            import fnmatch
            out = [t for t in out if fnmatch.fnmatch(t["action"], actions)]
        return out

    def all(self) -> List[Task]:
        with self._lock:
            return list(self._tasks.values())

    def stats(self) -> dict:
        return {"running": len(self._tasks), "completed": self.completed}
