"""Named host thread pools (reference `threadpool/ThreadPool.java`).

In this runtime the device does the heavy lifting asynchronously (XLA
dispatch is already non-blocking), so host pools serve what they serve in
the reference minus the scoring loops: IO-bound work — snapshot/flush
persistence, translog fsyncs — and fan-out coordination. Sizes follow the
reference's defaults scaled to the host core count."""

from __future__ import annotations

import contextvars
import os
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List


class NamedPool:
    def __init__(self, name: str, size: int):
        self.name = name
        self.size = size
        self._ex = ThreadPoolExecutor(max_workers=size,
                                      thread_name_prefix=f"ostpu-{name}")
        self.submitted = 0
        self.completed = 0

    def submit(self, fn: Callable, *args, **kw) -> Future:
        self.submitted += 1
        # carry the submitter's contextvars into the worker: tracer spans
        # started on the pool thread attach under the submitting request's
        # span instead of silently becoming detached roots (each task gets
        # its own context copy, so concurrent tasks can't clobber each
        # other's ambient span)
        ctx = contextvars.copy_context()

        def run():
            try:
                return ctx.run(fn, *args, **kw)
            finally:
                self.completed += 1

        return self._ex.submit(run)

    def stats(self) -> dict:
        return {"name": self.name, "size": self.size,
                "active": max(self.submitted - self.completed, 0),
                "completed": self.completed}

    def shutdown(self) -> None:
        self._ex.shutdown(wait=True)


class ThreadPools:
    """The node's pool set: search (msearch per-body fallback fan-out),
    write (bulk persistence), snapshot (repo IO), management (merges,
    refresh bookkeeping), generic.

    Waiting discipline (oslint OSL503): code coordinating with these pools
    blocks on `Future.result()` / `threading.Condition` / `Event`, never a
    `time.sleep` polling loop — a poll both wastes a core and adds up to a
    full poll interval of latency per hop."""

    def __init__(self, cores: int = 0):
        n = cores or os.cpu_count() or 1
        self.pools: Dict[str, NamedPool] = {
            # reference search pool sizing is ~1.5x cores; host search
            # work here is the msearch fallback + fetch fan-out, so a
            # modest cap keeps the GIL convoy bounded
            "search": NamedPool("search", max(2, min((3 * n) // 2, 12))),
            "write": NamedPool("write", max(1, n)),
            "snapshot": NamedPool("snapshot", max(1, min(n, 4))),
            "management": NamedPool("management", max(1, min(n, 2))),
            "generic": NamedPool("generic", max(1, min(4 * n, 16))),
        }

    def pool(self, name: str) -> NamedPool:
        return self.pools[name]

    def run_blocking(self, name: str, tasks: List[Callable]) -> list:
        """Fan a batch out on a pool and wait (coordinated IO barrier)."""
        futs = [self.pools[name].submit(t) for t in tasks]
        return [f.result() for f in futs]

    def stats(self) -> List[dict]:
        return [p.stats() for p in self.pools.values()]

    def shutdown(self) -> None:
        for p in self.pools.values():
            p.shutdown()
