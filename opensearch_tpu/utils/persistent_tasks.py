"""Persistent tasks: long-running work registered in durable cluster state
so it survives node restarts and resumes where it left off.

Reference: `persistent/AllocatedPersistentTask.java:1` +
`persistent/PersistentTasksClusterService.java:1` — tasks live in cluster
state metadata, get (re)allocated to nodes, checkpoint progress, and are
completed/cancelled through the cluster-state update path. The TPU-native
analog keeps the same state machine on one node: a JSON task table under
the node's data path, executor functions registered per task type, at-
least-once resume semantics with an opaque `progress` checkpoint the
executor maintains, and the same lifecycle verbs (start / update progress
/ complete / cancel).

Executors run on the node's generic thread pool when available, inline
otherwise; they receive (params, progress, checkpoint_fn) and return the
final result. An executor that raises marks the task `failed` (kept for
inspection, like the reference's failed allocations)."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

_STATES = ("running", "completed", "failed", "cancelled")


class PersistentTasksService:
    def __init__(self, data_path: Optional[str] = None, thread_pools=None):
        self.data_path = data_path
        self.thread_pools = thread_pools
        self.executors: Dict[str, Callable] = {}
        self.tasks: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._counter = 0
        if data_path:
            self._load()

    # ---------------- persistence ----------------

    def _file(self) -> Optional[str]:
        if not self.data_path:
            return None
        return os.path.join(self.data_path, "persistent_tasks.json")

    def _save(self) -> None:
        f = self._file()
        if f is None:
            return
        os.makedirs(self.data_path, exist_ok=True)
        tmp = f + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"tasks": self.tasks, "counter": self._counter}, fh)
        os.replace(tmp, f)

    def _load(self) -> None:
        f = self._file()
        if f is None or not os.path.exists(f):
            return
        with open(f) as fh:
            saved = json.load(fh)
        self.tasks = saved.get("tasks", {})
        self._counter = saved.get("counter", 0)
        # tasks that were running when the node died stay `running` —
        # resume_all() re-executes them from their checkpoint (the
        # reference reallocates on cluster-state recovery)

    # ---------------- registry ----------------

    def register_executor(self, task_type: str, fn: Callable) -> None:
        """fn(params: dict, progress: dict, checkpoint: Callable[[dict],
        None]) -> dict. `checkpoint` persists intermediate progress; on
        resume the executor sees the last checkpointed progress."""
        self.executors[task_type] = fn

    # ---------------- lifecycle ----------------

    def start(self, task_type: str, params: Optional[dict] = None,
              task_id: Optional[str] = None, run: bool = True) -> dict:
        if task_type not in self.executors:
            raise ValueError(f"no executor for task type [{task_type}]")
        with self._lock:
            self._counter += 1
            tid = task_id or f"{task_type}-{self._counter}"
            if tid in self.tasks and \
                    self.tasks[tid]["state"] == "running":
                raise ValueError(f"persistent task [{tid}] already running")
            task = {"id": tid, "type": task_type, "params": params or {},
                    "state": "running", "progress": {},
                    "started_ts": time.time(), "result": None,
                    "error": None}
            self.tasks[tid] = task
            self._save()
        if run:
            self._execute(tid)
        return dict(self.tasks[tid])

    def _execute(self, tid: str) -> None:
        def body():
            task = self.tasks[tid]
            fn = self.executors[task["type"]]

            def checkpoint(progress: dict) -> None:
                with self._lock:
                    if self.tasks.get(tid, {}).get("state") == "cancelled":
                        raise TaskCancelled(tid)
                    task["progress"] = dict(progress)
                    self._save()

            try:
                result = fn(task["params"], dict(task["progress"]),
                            checkpoint)
                with self._lock:
                    if task["state"] == "running":
                        task["state"] = "completed"
                        task["result"] = result
                        task["completed_ts"] = time.time()
                        self._save()
            except TaskCancelled:
                pass        # state already set by cancel()
            except Exception as e:                     # noqa: BLE001
                with self._lock:
                    # a cancel that raced the failure wins: the user's
                    # explicit verb must not be overwritten by `failed`
                    if task["state"] == "running":
                        task["state"] = "failed"
                        task["error"] = f"{type(e).__name__}: {e}"
                        self._save()

        if self.thread_pools is not None:
            self.thread_pools.pool("generic").submit(body)
        else:
            body()

    def resume_all(self) -> int:
        """Re-execute every task that was `running` at the last shutdown
        (called after node recovery). Executors must be re-registered
        first; a running task with no executor becomes `failed`."""
        resumed = 0
        # decide everything under the lock FIRST (state flips + one save),
        # then kick executors — _save() iterating self.tasks must not race
        # an already-resumed executor mutating its task dict
        to_run = []
        with self._lock:
            for tid, task in self.tasks.items():
                if task["state"] != "running":
                    continue
                if task["type"] not in self.executors:
                    task["state"] = "failed"
                    task["error"] = "no executor registered after restart"
                else:
                    to_run.append(tid)
            self._save()
        for tid in to_run:
            self._execute(tid)
            resumed += 1
        return resumed

    def cancel(self, tid: str) -> bool:
        with self._lock:
            task = self.tasks.get(tid)
            if task is None or task["state"] != "running":
                return False
            task["state"] = "cancelled"
            task["cancelled_ts"] = time.time()
            self._save()
            return True

    def get(self, tid: str) -> Optional[dict]:
        t = self.tasks.get(tid)
        return dict(t) if t else None

    def list(self, task_type: Optional[str] = None) -> list:
        return [dict(t) for t in self.tasks.values()
                if task_type is None or t["type"] == task_type]

    def stats(self) -> dict:
        by_state: Dict[str, int] = {}
        for t in self.tasks.values():
            by_state[t["state"]] = by_state.get(t["state"], 0) + 1
        return {"count": len(self.tasks), "by_state": by_state}


class TaskCancelled(Exception):
    pass
