"""Search/indexing slow logs (reference `index/SearchSlowLog.java`,
`index/IndexingSlowLog.java`): per-index thresholds from settings
(`index.search.slowlog.threshold.query.warn` etc.), emitted to the standard
`logging` tree and kept in an inspectable ring buffer for the stats APIs."""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Deque, Dict, Optional

LEVELS = ("warn", "info", "debug", "trace")
_LOG_LEVEL = {"warn": logging.WARNING, "info": logging.INFO,
              "debug": logging.DEBUG, "trace": logging.DEBUG}


def _parse_thresholds(settings: dict, section: str, op: str) -> Dict[str, float]:
    """settings like {"index": {"search": {"slowlog": {"threshold": {"query":
    {"warn": "1s", ...}}}}}} (or the flattened dotted form) -> seconds."""
    out: Dict[str, float] = {}
    idx = settings.get("index", settings)
    node: Any = idx
    for part in (section, "slowlog", "threshold", op):
        node = node.get(part, {}) if isinstance(node, dict) else {}
    prefixes = (f"{section}.slowlog.threshold.{op}.",
                f"index.{section}.slowlog.threshold.{op}.")
    flat = {k.split(".")[-1]: v
            for src in (settings or {}, idx) if isinstance(src, dict)
            for k, v in src.items()
            if isinstance(k, str) and k.startswith(prefixes)}
    merged = dict(node) if isinstance(node, dict) else {}
    merged.update(flat)
    for level, raw in merged.items():
        if level not in LEVELS or raw in (None, "", "-1", -1):
            continue
        out[level] = _time_s(raw)
    return out


def _time_s(v) -> float:
    if isinstance(v, (int, float)):
        return float(v) / 1000.0
    s = str(v).strip()
    for suf, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0)):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s) / 1000.0


class SlowLog:
    def __init__(self, index_name: str, settings: dict, section: str,
                 op: str, source_limit: int = 1000):
        self.index = index_name
        self.thresholds = _parse_thresholds(settings or {}, section, op)
        self.logger = logging.getLogger(
            f"opensearch_tpu.{section}.slowlog.{op}")
        self.entries: Deque[dict] = deque(maxlen=256)
        self.source_limit = source_limit

    def maybe_log(self, took_s: float, source: Any,
                  extra=None, timeline_id: int = 0) -> Optional[str]:
        """Log at the most severe threshold `took_s` crosses; returns the
        level (for tests/stats) or None.

        `extra` enriches the entry with attribution — WHY the operation
        was slow, not just how long: ladder-rung counters, the request's
        root trace span, the rescore path. A dict merges directly; a
        callable is invoked only when a threshold actually fires, so the
        (possibly deep) span serialization costs nothing on fast
        requests.

        `timeline_id` links the entry to the request's flight-recorder
        timeline (obs/flight_recorder.py) and makes the threshold a dump
        trigger: a slow query's full event journal is frozen the moment
        the slowlog fires, before the ring can overwrite it."""
        hit = None
        for level in LEVELS:           # warn is most severe; first hit wins
            thr = self.thresholds.get(level)
            if thr is not None and took_s >= thr:
                hit = level
                break
        if hit is None:
            return None
        msg = str(source)[: self.source_limit]
        entry = {"index": self.index, "level": hit,
                 "took_millis": int(took_s * 1000), "source": msg,
                 "timestamp": time.time()}
        if callable(extra):
            extra = extra()
        if isinstance(extra, dict):
            entry.update(extra)
        if timeline_id:
            entry["flight_recorder_timeline"] = timeline_id
        self.entries.append(entry)
        self.logger.log(_LOG_LEVEL[hit],
                        "[%s] took[%dms], source[%s]",
                        self.index, entry["took_millis"], msg)
        if timeline_id:
            # slow-threshold crossing = anomaly trigger: freeze this
            # request's timeline (lazy import: utils must stay importable
            # without obs, and the cost lands only on slow requests)
            from ..obs.flight_recorder import RECORDER
            RECORDER.trigger(
                "slowlog", [timeline_id],
                note=f"[{self.index}] {hit} threshold: "
                     f"{entry['took_millis']}ms")
        return hit

    def stats(self) -> dict:
        return {"thresholds": self.thresholds,
                "recent": list(self.entries)[-10:]}
