"""Deadline-aware parallel legs — the fan-out/join primitive shared by
hybrid fusion, the distributed scatter phases, and the federation
scrapes (ROADMAP item 3).

A *leg* is one independent branch of a request: one hybrid
sub-retrieval, one member's shard group in a scatter round, one remote
scrape.  The serial coordinator loops made request latency the SUM of
leg latencies; a :class:`LegSet` makes it the MAX while changing
nothing else:

- **Context travels with the leg.**  Every ``add_leg`` captures
  ``contextvars.copy_context()``, so the ambient :class:`Deadline`,
  the tracer span stack, the flight-recorder timeline, the insights
  Observation and the query-cost accumulator all follow the leg onto
  its worker thread — the same discipline as ``NamedPool.submit``.
- **Joins honor the ambient deadline.**  ``join()`` waits for each leg
  at most ``remaining + grace``; a leg that does not come back in time
  is *abandoned* (``leg.wedged``) rather than waited on forever, so a
  wedged member costs one cap, not the whole request.
- **Exceptions are captured per leg**, never lost and never allowed to
  tear down sibling legs.  Callers decide the merge policy: fusion
  re-raises the first error in sub-query order, the scatter converts
  member errors into failover re-planning.
- **Results come back in add order** regardless of completion order,
  which is what makes the serial and parallel arms byte-identical:
  every merge step downstream of a join sees the same inputs in the
  same order.

Serial arm: ``OPENSEARCH_TPU_LEGS=0`` (or ``LegSet(parallel=False)``)
runs the legs in add order on the caller's thread — same contexts,
same leg paths, same outcome objects — so bench pairs and parity
tests compare *scheduling only*.

Determinism hook: each leg runs under a stable *leg path*
(``parent/label:name``, exposed via :func:`current_path`).  The chaos
harness keys its per-rule call counters and probability draws by this
path, which is a pure function of request structure rather than thread
interleaving — seeded fault journals replay byte-identically whether
legs run serial or parallel (see ``cluster/faults.py``).

Nested fan-outs (a hybrid sub-retrieval that is itself a distributed
search which scatters again) must never share a pool with their
parents: a parent leg blocked in ``join()`` could occupy the only pool
slot its children need (classic pool-starvation deadlock).  Each
fan-out DEPTH therefore gets its own bounded pool — a leg at depth d
only ever waits on depth d+1, so per-depth pools cannot form a wait
cycle — and depths past ``_POOLED_DEPTH`` spill to dedicated per-leg
threads.  Depth is tracked with a context variable so the scheduling
decision needs no global coordination.

When a depth pool is saturated (every slot busy — the process is
already running as many legs as it has workers), overflow legs are NOT
queued: they run inline on the joining caller's thread (caller-runs,
counted in ``legs.inline_overflow``).  Queueing behind a saturated
pool buys no parallelism, only queue latency; caller-runs makes the
fan-out degrade gracefully toward the serial arm under load.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Any, Callable, List, Optional

from . import deadline as _dl
from .metrics import METRICS
from .trace import TRACER

__all__ = ["Leg", "LegSet", "LegWedged", "enabled", "current_path",
           "pool_stats"]

# Extra time join() grants a leg past the ambient deadline before
# abandoning it.  Legs are themselves deadline-aware (RPC socket
# timeouts are derived from the same Deadline), so in practice they
# return within the budget; the grace only bounds how long a truly
# wedged leg can hold the join.
JOIN_GRACE_S = 0.5

# Hard cap on a join wait when there is neither an ambient deadline nor
# an explicit timeout.  High enough to never trip in tests or serving
# (blackhole caps at 2 s, scrape caps are single-digit seconds); its
# only job is making "no deadline + wedged member" survivable.
JOIN_DEFAULT_CAP_S = 120.0


def enabled() -> bool:
    """Parallel arm toggle (``OPENSEARCH_TPU_LEGS``, default on).

    Read per call so tests and bench pairs can flip arms without
    re-importing; serial mode keeps LegSet semantics (contexts, leg
    paths, outcome objects) and changes only the scheduling.
    """
    return os.environ.get("OPENSEARCH_TPU_LEGS", "1").lower() not in (
        "0", "false", "no", "off")


class LegWedged(Exception):
    """A leg did not return within the join budget and was abandoned.

    The leg's thread may still complete later; its result is discarded.
    Scatter treats this like deadline exhaustion for the leg's shards.
    """


# ---------------------------------------------------------------------------
# leg identity
# ---------------------------------------------------------------------------

# "" at top level; "hybrid.sub:1/dist.query_phase:rb" two levels down.
_path: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ostpu_leg_path", default="")


def current_path() -> str:
    """Stable identity of the currently executing leg ("" outside legs).

    Deterministic across serial/parallel arms and across replays — the
    chaos harness keys seeded draws by it.
    """
    return _path.get()


def _depth() -> int:
    p = _path.get()
    return 0 if not p else p.count("/") + 1


# ---------------------------------------------------------------------------
# shared bounded pools, one per fan-out depth (deeper levels spill)
# ---------------------------------------------------------------------------

# A leg at depth d only ever blocks on resources at depth d+1, so pools
# keyed BY depth can never deadlock each other: level-0 legs (hybrid
# subs) park in join() waiting on level-1 legs (scatter members), which
# wait on level-2+ legs running on dedicated threads.  Capping the
# pooled levels at _POOLED_DEPTH keeps the thread budget bounded while
# sparing the two hot fan-out layers the per-leg thread-spawn cost.
_POOLED_DEPTH = 2          # depths 0..1 pooled; deeper legs spill

_pool_lock = threading.Lock()
_pools: dict = {}          # depth -> ThreadPoolExecutor
_slots: dict = {}          # depth -> Semaphore(max_workers)


def _pool_size() -> int:
    try:
        ncpu = os.cpu_count() or 8
    except Exception:  # pragma: no cover
        ncpu = 8
    return max(8, min(4 * ncpu, 32))


def _get_pool(depth: int):
    """-> (pool, slots) for a pooled depth, (None, None) past it."""
    if depth >= _POOLED_DEPTH:
        return None, None
    p = _pools.get(depth)
    if p is None:
        with _pool_lock:
            p = _pools.get(depth)
            if p is None:
                p = ThreadPoolExecutor(
                    max_workers=_pool_size(),
                    thread_name_prefix=f"ostpu-legs{depth}")
                _slots[depth] = threading.Semaphore(_pool_size())
                _pools[depth] = p
    return p, _slots[depth]


def pool_stats() -> dict:
    """Introspection for tests and the stats endpoint."""
    with _pool_lock:
        pools = dict(_pools)
    return {"created": bool(pools),
            "max_workers": _pool_size(),
            "levels": {d: {"max_workers": p._max_workers,
                           "threads": len(p._threads)}
                       for d, p in sorted(pools.items())},
            "threads": sum(len(p._threads) for p in pools.values())}


# ---------------------------------------------------------------------------
# outcome object
# ---------------------------------------------------------------------------

class Leg:
    """One branch of a fan-out: callable + captured context + outcome."""

    __slots__ = ("name", "fn", "ctx", "path", "future", "value", "error",
                 "wedged", "duration_ms")

    def __init__(self, name: str, fn: Callable[[], Any], ctx, path: str):
        self.name = name
        self.fn = fn
        self.ctx = ctx
        self.path = path
        self.future: Optional[Future] = None
        self.value: Any = None
        self.error: Optional[BaseException] = None
        self.wedged = False
        self.duration_ms = 0.0

    @property
    def ok(self) -> bool:
        return self.error is None and not self.wedged

    def result(self) -> Any:
        """Value or raise — for callers with propagate-first semantics."""
        if self.error is not None:
            raise self.error
        return self.value


class LegSet:
    """Deadline-aware fan-out/join over context-carrying legs.

    Usage::

        ls = LegSet("hybrid.sub")
        for i, sb in enumerate(bodies):
            ls.add_leg(lambda sb=sb: run_sub(sb), name=str(i))
        for leg in ls.join():          # add order, errors captured
            ...

    ``join()`` may be called exactly once; the LegSet is single-shot.
    """

    def __init__(self, label: str, parallel: Optional[bool] = None):
        self.label = label
        self.parallel = enabled() if parallel is None else bool(parallel)
        self.legs: List[Leg] = []
        self._joined = False

    # -- build ------------------------------------------------------------

    def add_leg(self, fn: Callable[[], Any], name: Optional[str] = None) -> Leg:
        """Register a leg.  Context (deadline/trace/obs/insights/cost) is
        captured NOW, on the caller's thread."""
        if self._joined:
            raise RuntimeError("LegSet already joined")
        name = str(len(self.legs)) if name is None else str(name)
        parent = _path.get()
        path = (parent + "/" if parent else "") + f"{self.label}:{name}"
        leg = Leg(name, fn, contextvars.copy_context(), path)
        self.legs.append(leg)
        return leg

    # -- run --------------------------------------------------------------

    def _run_leg(self, leg: Leg) -> None:
        """Body of one leg; runs inside leg.ctx.  Never raises."""
        tok = _path.set(leg.path)
        t0 = time.monotonic()
        try:
            with TRACER.span("legs.leg", label=self.label, leg=leg.name):
                leg.value = leg.fn()
        except BaseException as e:  # captured, merged by the caller
            leg.error = e
        finally:
            _path.reset(tok)
            leg.duration_ms = (time.monotonic() - t0) * 1000.0
            self._record_leg(leg)

    def _record_leg(self, leg: Leg) -> None:
        from ..obs import flight_recorder as _fr
        if _fr.RECORDER.enabled:
            tl = _fr.current()
            if tl:
                _fr.RECORDER.record(
                    tl, "legs.leg", label=self.label, name=leg.name,
                    ms=round(leg.duration_ms, 3), ok=leg.error is None,
                    err=(type(leg.error).__name__
                         if leg.error is not None else None))

    def _launch(self) -> List[Leg]:
        """Dispatch legs; return the ones deferred to the caller thread.

        Caller-runs overflow: when the depth pool's slots are all busy
        (the process is saturated with concurrent fan-outs), queueing a
        leg behind the pool buys no parallelism — it only adds queue
        latency and context switches.  Those legs are run inline on the
        caller's thread during join(), which is parked waiting anyway;
        under saturation the fan-out degrades gracefully toward the
        serial arm instead of convoying behind a shared queue.
        """
        pool, slots = _get_pool(_depth())
        inline: List[Leg] = []
        for leg in self.legs:
            fut: Future = Future()

            def run(leg=leg, fut=fut, release=False):
                try:
                    leg.ctx.run(self._run_leg, leg)
                finally:
                    if release:
                        slots.release()
                    fut.set_result(None)

            if pool is not None:
                if slots.acquire(blocking=False):
                    leg.future = pool.submit(run, release=True)
                else:
                    METRICS.counter("legs.inline_overflow").inc()
                    leg.future = fut
                    inline.append(leg)
            else:
                # Deep fan-out (depth >= _POOLED_DEPTH): dedicated
                # thread per leg so a parent leg parked in join() can't
                # starve its children of pool slots.
                leg.future = fut
                t = threading.Thread(
                    target=run, name=f"ostpu-leg-{leg.path}", daemon=True)
                t.start()
        return inline

    # -- join -------------------------------------------------------------

    def join(self, timeout_s: Optional[float] = None) -> List[Leg]:
        """Run/await every leg; return them in add order.

        Parallel arm: waits each leg up to ``ambient-deadline remaining
        + JOIN_GRACE_S`` (or ``timeout_s`` when no deadline); a leg that
        misses the window is abandoned with ``wedged=True`` and a
        :class:`LegWedged` error.  Serial arm: runs legs in add order on
        this thread (no abandonment — each leg is deadline-aware
        itself).
        """
        if self._joined:
            raise RuntimeError("LegSet already joined")
        self._joined = True
        n = len(self.legs)
        if n == 0:
            return self.legs
        t0 = time.monotonic()
        run_parallel = self.parallel and n > 1
        if not run_parallel:
            for leg in self.legs:
                leg.ctx.run(self._run_leg, leg)
        else:
            inline = self._launch()
            # Overflow legs run here, on the caller thread, while the
            # pooled legs execute — the caller would only be parked in
            # the wait loop below otherwise.  Add order is preserved
            # within the inline subset; results merge in add order
            # regardless.
            for leg in inline:
                leg.ctx.run(self._run_leg, leg)
                leg.future.set_result(None)
            dl = _dl.current()
            for leg in self.legs:
                while True:
                    if dl is not None:
                        wait = max(dl.remaining_s(), 0.0) + JOIN_GRACE_S
                    elif timeout_s is not None:
                        wait = max(timeout_s - (time.monotonic() - t0), 0.0)
                    else:
                        wait = JOIN_DEFAULT_CAP_S
                    try:
                        leg.future.result(timeout=wait)
                        break
                    except _FutTimeout:
                        leg.wedged = True
                        leg.error = LegWedged(
                            f"leg {leg.path} abandoned after "
                            f"{time.monotonic() - t0:.3f}s")
                        METRICS.counter("legs.wedged").inc()
                        break
        self._account(t0, run_parallel)
        return self.legs

    def _account(self, t0: float, ran_parallel: bool) -> None:
        wall_ms = (time.monotonic() - t0) * 1000.0
        done = [leg for leg in self.legs if not leg.wedged]
        METRICS.counter("legs.joins").inc()
        METRICS.counter("legs.launched").inc(len(self.legs))
        METRICS.counter("legs.completed").inc(len(done))
        nerr = sum(1 for leg in done if leg.error is not None)
        if nerr:
            METRICS.counter("legs.errors").inc(nerr)
        if METRICS.enabled:
            METRICS.histogram("legs.fanout").record(len(self.legs))
            METRICS.histogram("legs.join_ms").record(wall_ms)
            for leg in done:
                METRICS.histogram("legs.leg_ms").record(leg.duration_ms)
            if ran_parallel and wall_ms > 0.0:
                # >1.0 means legs actually overlapped; == 1.0 is serial.
                overlap = sum(leg.duration_ms for leg in done) / wall_ms
                METRICS.histogram("legs.overlap").record(overlap)
        from ..obs import flight_recorder as _fr
        if _fr.RECORDER.enabled:
            tl = _fr.current()
            if tl:
                _fr.RECORDER.record(
                    tl, "legs.join", label=self.label, n=len(self.legs),
                    ms=round(wall_ms, 3), parallel=ran_parallel,
                    wedged=len(self.legs) - len(done), errors=nerr)


def run_legs(label: str, fns: List[Callable[[], Any]],
             names: Optional[List[str]] = None,
             parallel: Optional[bool] = None) -> List[Leg]:
    """One-shot convenience: build a LegSet, add ``fns``, join."""
    ls = LegSet(label, parallel=parallel)
    for i, fn in enumerate(fns):
        ls.add_leg(fn, name=None if names is None else names[i])
    return ls.join()
