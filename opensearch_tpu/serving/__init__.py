"""Serving layer: cross-request dynamic batching (docs/SERVING.md) and
the closed-loop remediation actuator (docs/RESILIENCE.md
"Self-healing loop")."""

from .remediator import REMEDIATOR, Action, RemediationConfig, Remediator
from .scheduler import LANES, SchedulerConfig, ServingScheduler

__all__ = ["ServingScheduler", "SchedulerConfig", "LANES",
           "Remediator", "RemediationConfig", "Action", "REMEDIATOR"]
