"""Serving layer: cross-request dynamic batching (docs/SERVING.md)."""

from .scheduler import LANES, SchedulerConfig, ServingScheduler

__all__ = ["ServingScheduler", "SchedulerConfig", "LANES"]
