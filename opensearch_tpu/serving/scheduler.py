"""Serving scheduler: cross-request dynamic batching with deadline-aware
flush and priority lanes (docs/SERVING.md).

The runtime's device programs batch over the QUERY axis (mesh
`try_msearch` groups, fastpath `msearch_batched` kernel grids), but only
queries arriving inside one `_msearch` body ever shared a launch —
concurrent independent searches from `ThreadingHTTPServer` threads each
paid their own dispatch and serialized on the chip. This scheduler sits
between the REST layer and `MeshSearchService`: eligible searches enqueue
into a bounded two-lane queue, and a single dispatcher thread flushes the
pending set as ONE batched program invocation when either `max_batch`
requests are waiting or the oldest has waited `max_wait_us` (whichever
first). Per-request futures carry results, errors and timeouts back to
the submitting HTTP threads.

Contracts:

- **Bit-identical results.** A flushed batch rides the exact query-axis
  batching `_msearch` already uses (`MeshSearchService.try_msearch`,
  `executor.msearch_batched`); per-query scoring is independent of batch
  composition (pow2 query padding, per-row f32 accumulation, per-query
  top-k merge), so a coalesced search serves the same pages, scores and
  tie-breaks as a direct one — the f32 tie-serve contract from
  docs/FASTPATH.md is untouched. `SchedulerConfig.oracle` (env
  `OPENSEARCH_TPU_SCHED_ORACLE=1`) re-runs every coalesced body through
  the direct path on the dispatcher thread and counts mismatches.
- **Graceful degradation.** Non-coalescable shapes bypass the queue
  unchanged (`accepts`); a closed scheduler, an entry still queued at
  the request timeout (wedged dispatcher), or a batch execution error
  falls back to direct per-request execution (an entry already claimed
  into an in-flight batch is waited out, not duplicated) — the scheduler
  can only ever make an eligible request *batched*, never make it fail.
- **Cancellation.** A cancelled `utils/tasks.py` task is dropped from the
  pending set before launch: `Task.on_cancel` wakes the scheduler, which
  resolves the entry with `TaskCancelledException` without dispatching it.
- **Admission.** The queue is bounded (`queue_cap`); a full queue rejects
  with `PressureRejectedException` (HTTP 429) and is counted by
  `SearchBackpressureService` — concurrency converts to backpressure, not
  unbounded growth.

Lanes: requests carry a lane from their `utils/wlm.py` workload group
("interactive" default; groups configured with `lane: "batch"`, and
scroll-initiating searches, ride the batch lane). At flush time the
interactive lane preempts the batch lane: interactive entries fill the
batch first, batch/scroll entries only take the leftover slots.

All waiting uses `threading.Condition` / `threading.Event` — no sleep
polling (oslint OSL503, docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import copy as _copy
import json as _json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..utils.metrics import METRICS, MetricsRegistry
from ..utils.tasks import TaskCancelledException
from ..utils.wlm import PressureRejectedException

LANES = ("interactive", "batch")

# body keys MeshSearchService._eligible statically declines — queueing
# these shapes would add latency for a guaranteed host-loop outcome, so
# they bypass the scheduler unchanged (the decline still happens at the
# same place it does today, with the same attribution)
_BYPASS_KEYS = ("knn", "rescore", "min_score", "profile", "collapse",
                "suggest", "search_after", "highlight", "script_fields")

# entry states (transitions under the scheduler condition lock)
_QUEUED, _CLAIMED, _DONE, _ABANDONED = "queued", "claimed", "done", "abandoned"


class SchedulerConfig:
    """Tuning knobs (env defaults; see docs/SERVING.md for the
    latency/throughput trade-off each one moves)."""

    def __init__(self, max_batch: Optional[int] = None,
                 max_wait_us: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 oracle: Optional[bool] = None,
                 kernel_batching: bool = True,
                 request_timeout_s: float = 30.0,
                 idle_timeout_s: float = 5.0):
        env = os.environ
        self.max_batch = int(max_batch if max_batch is not None
                             else env.get("OPENSEARCH_TPU_SCHED_MAX_BATCH",
                                          32))
        self.max_wait_us = int(max_wait_us if max_wait_us is not None
                               else env.get(
                                   "OPENSEARCH_TPU_SCHED_MAX_WAIT_US", 1000))
        self.queue_cap = int(queue_cap if queue_cap is not None
                             else env.get("OPENSEARCH_TPU_SCHED_QUEUE_CAP",
                                          256))
        if oracle is None:
            oracle = env.get("OPENSEARCH_TPU_SCHED_ORACLE",
                             "") not in ("", "0")
        self.oracle = bool(oracle)
        # also coalesce mesh-declined / mesh-less bodies through the
        # fastpath's grouped kernel launches (executor.msearch_batched)
        self.kernel_batching = bool(kernel_batching)
        self.request_timeout_s = float(request_timeout_s)
        self.idle_timeout_s = float(idle_timeout_s)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        if self.queue_cap < 1:
            raise ValueError("queue_cap must be >= 1")


class _Pending:
    __slots__ = ("name", "svc", "body", "lane", "task", "enq", "done",
                 "resp", "error", "state")

    def __init__(self, name: str, svc, body: dict, lane: str, task):
        self.name = name
        self.svc = svc
        self.body = body
        self.lane = lane
        self.task = task
        self.enq = time.monotonic()
        self.done = threading.Event()
        self.resp = None            # response dict, or None (-> host loop)
        self.error: Optional[BaseException] = None
        self.state = _QUEUED


class ServingScheduler:
    """One per Node. `execute()` is the only entry point the search path
    uses; everything else is dispatcher machinery and telemetry."""

    def __init__(self, node, config: Optional[SchedulerConfig] = None,
                 enabled: Optional[bool] = None):
        self.node = node
        self.config = config or SchedulerConfig()
        if enabled is None:
            flag = os.environ.get("OPENSEARCH_TPU_SCHED")
            if flag is not None:
                enabled = flag not in ("", "0")
            else:
                # default: on whenever there is a device batching substrate
                # worth coalescing for (the SPMD mesh); single-chip nodes
                # opt in with OPENSEARCH_TPU_SCHED=1 (kernel batching)
                enabled = node.mesh_service is not None
        self.enabled = bool(enabled)
        self._cond = threading.Condition()
        self._lanes: Dict[str, deque] = {lane: deque() for lane in LANES}
        self._pending = 0
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # counters (mutated under self._cond; mirrored into METRICS)
        self.submitted = 0
        self.batched_served = 0     # resolved with a batched response
        self.declined = 0           # resolved None -> host loop
        self.bypassed = 0           # accepts() said no -> direct path
        self.rejected = 0           # queue full -> 429
        self.cancelled_dropped = 0  # dropped before launch
        self.direct_fallbacks = 0   # degraded mode: ran direct
        self.batch_errors = 0
        self.flushes = 0
        self.flush_reasons = {"size": 0, "deadline": 0, "drain": 0}
        self.lane_flushed = {lane: 0 for lane in LANES}
        self.oracle_checks = 0
        self.oracle_mismatches = 0
        self.last_oracle_mismatch: Optional[str] = None
        # per-instance histogram mirror: the process-global METRICS
        # registry feeds /_metrics, but THIS node's `_nodes/stats` block
        # must not blend in a co-resident node's flushes (remote-cluster
        # peers, multi-node tests share the process)
        self._local = MetricsRegistry()

    # ---------------- eligibility ----------------

    def accepts(self, body) -> bool:
        """Cheap coalescability screen. Permissive by design: anything it
        lets through still goes through the mesh/fastpath's own
        eligibility and falls back to the host loop on decline; this only
        spares statically-hopeless shapes the queue wait."""
        if not isinstance(body, dict):
            return False
        for k in _BYPASS_KEYS:
            if body.get(k) is not None:
                return False
        q = body.get("query")
        if q is not None and not isinstance(q, dict):
            return False
        return True

    # ---------------- request side ----------------

    def execute(self, name: str, svc, body: dict, task=None,
                lane: str = "interactive"):
        """Coalesce one eligible search into the next flushed batch.
        Returns the batched response dict, or None when the batch path
        declined the body (caller runs the host shard loop — identical to
        a direct mesh decline). Raises PressureRejectedException when the
        queue is full and TaskCancelledException when the request's task
        was cancelled before launch."""
        if lane not in self._lanes:
            lane = "interactive"
        entry = _Pending(name, svc, body, lane, task)
        # ONE critical section for closed-check, admission, dispatcher
        # liveness and enqueue: the dispatcher's idle-exit decision runs
        # under the same condition, so an entry can never land in the
        # queue with no dispatcher alive and none restarted
        with self._cond:
            if self._closed:
                self.direct_fallbacks += 1
                METRICS.counter("serving.direct_fallbacks").inc()
                closed = True
            elif self._pending >= self.config.queue_cap:
                self.rejected += 1
                METRICS.counter("serving.rejected").inc()
                self.node.search_backpressure.note_queue_rejection()
                raise PressureRejectedException(
                    f"serving scheduler queue full "
                    f"({self._pending}/{self.config.queue_cap} pending); "
                    f"rejecting search")
            else:
                closed = False
                if not self._dispatcher_alive():
                    self._start_dispatcher()
                self.submitted += 1
                METRICS.counter("serving.submitted").inc()
                METRICS.counter(f"serving.lane.{lane}.submitted").inc()
                self._lanes[lane].append(entry)
                self._pending += 1
                METRICS.gauge("serving.queue_depth").set(self._pending)
                self._cond.notify_all()
        if closed:
            return self._direct(name, svc, body)
        if task is not None and hasattr(task, "on_cancel"):
            # wake + drop the entry the moment its task is cancelled (the
            # flush assembly re-checks as a backstop)
            task.on_cancel(lambda _t, e=entry: self._drop_cancelled(e))
        return self._await(entry)

    def _await(self, entry: _Pending):
        if not entry.done.wait(self.config.request_timeout_s):
            with self._cond:
                if entry.state == _QUEUED:
                    # scheduler wedged with the entry still queued: pull it
                    # and degrade to direct execution on this thread
                    try:
                        self._lanes[entry.lane].remove(entry)
                        self._pending -= 1
                        METRICS.gauge("serving.queue_depth").set(
                            self._pending)
                        self._cond.notify_all()
                    except ValueError:
                        pass
                    entry.state = _ABANDONED
                    self.direct_fallbacks += 1
                    METRICS.counter("serving.direct_fallbacks").inc()
            if entry.state == _ABANDONED:
                return self._direct(entry.name, entry.svc, entry.body)
            # claimed: the batch is in flight on the device — duplicating
            # it would be wasteful, so wait it out
            entry.done.wait()
        if entry.error is not None:
            raise entry.error
        return entry.resp

    def _drop_cancelled(self, entry: _Pending) -> None:
        with self._cond:
            if entry.state != _QUEUED:
                return
            try:
                self._lanes[entry.lane].remove(entry)
                self._pending -= 1
                METRICS.gauge("serving.queue_depth").set(self._pending)
                self._cond.notify_all()      # wake drain() waiters
            except ValueError:
                return
            self._resolve_cancelled(entry)

    def _resolve_cancelled(self, entry: _Pending) -> None:
        entry.state = _DONE
        entry.error = TaskCancelledException(
            f"task [{getattr(entry.task, 'id', '?')}] cancelled while "
            f"queued for batch dispatch: "
            f"{getattr(entry.task, 'cancel_reason', None)}")
        self.cancelled_dropped += 1
        METRICS.counter("serving.cancelled_dropped").inc()
        entry.done.set()

    # ---------------- dispatcher side ----------------

    def _dispatcher_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _start_dispatcher(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="ostpu-serving-dispatcher",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cond:
                # idle wait: exit after idle_timeout so test suites that
                # spin up hundreds of Nodes don't accumulate parked
                # threads; submit() restarts the dispatcher lazily
                while self._pending == 0 and not self._closed:
                    if not self._cond.wait(self.config.idle_timeout_s) \
                            and self._pending == 0:
                        if self._thread is me:
                            self._thread = None
                        return
                if self._closed and self._pending == 0:
                    return
                reason = self._wait_flush()
                if self._pending == 0:
                    continue
                batch = self._assemble(reason)
            if batch:
                try:
                    self._dispatch(batch)
                except BaseException:           # noqa: BLE001
                    # never strand claimed entries: whatever killed the
                    # dispatch, every waiter degrades to the host loop
                    for e in batch:
                        if not e.done.is_set():
                            e.resp = None
                            e.state = _DONE
                            e.done.set()
                    raise

    def _wait_flush(self) -> str:
        """Block (under the cond) until the flush policy fires: size
        (max_batch pending) or deadline (oldest waited max_wait_us)."""
        max_wait_s = self.config.max_wait_us / 1e6
        while True:
            if self._closed:
                return "drain"
            if self._pending >= self.config.max_batch:
                return "size"
            heads = [self._lanes[lane][0].enq for lane in LANES
                     if self._lanes[lane]]
            oldest = min(heads) if heads else None
            if oldest is None:
                return "deadline"     # emptied while we slept
            remaining = max_wait_s - (time.monotonic() - oldest)
            if remaining <= 0:
                return "deadline"
            self._cond.wait(remaining)

    def _assemble(self, reason: str) -> List[_Pending]:
        """Pop up to max_batch entries — interactive lane first (FIFO
        within a lane, batch/scroll lane fills the leftover slots) — and
        drop entries whose task was cancelled while queued. One slot is
        reserved for the batch lane whenever it has waiters: preemption
        means the interactive lane goes first, not that sustained
        interactive saturation starves scroll traffic into its request
        timeout."""
        batch: List[_Pending] = []
        for lane in LANES:                  # interactive preempts batch
            cap = self.config.max_batch
            if lane == "interactive" and self._lanes["batch"] and cap > 1:
                cap -= 1                    # starvation guard
            q = self._lanes[lane]
            while q and len(batch) < cap:
                entry = q.popleft()
                self._pending -= 1
                if entry.task is not None and \
                        getattr(entry.task, "cancelled", False):
                    self._resolve_cancelled(entry)
                    continue
                entry.state = _CLAIMED
                batch.append(entry)
                self.lane_flushed[lane] += 1
                METRICS.counter(f"serving.lane.{lane}.flushed").inc()
        METRICS.gauge("serving.queue_depth").set(self._pending)
        self._cond.notify_all()          # wake drain() waiters
        if batch:
            self.flushes += 1
            self.flush_reasons[reason] = \
                self.flush_reasons.get(reason, 0) + 1
            METRICS.counter(f"serving.flush.{reason}").inc()
            METRICS.histogram("serving.batch_size").record(len(batch))
            self._local.histogram("serving.batch_size").record(len(batch))
            now = time.monotonic()
            for e in batch:
                wait_ms = (now - e.enq) * 1000.0
                METRICS.histogram("serving.queue_wait").record(wait_ms)
                self._local.histogram("serving.queue_wait").record(wait_ms)
        return batch

    def _dispatch(self, batch: List[_Pending]) -> None:
        """Run the flushed batch grouped by index and hand every entry its
        result. Never raises: a failed group degrades its entries to the
        host loop (resp None)."""
        # group by (name, service identity), not name alone: two entries
        # can hold DIFFERENT IndexService snapshots for one name (index
        # deleted + recreated between their enqueues) and each must be
        # served from its own service, like the direct path would
        groups: Dict[tuple, List[_Pending]] = {}
        for e in batch:
            groups.setdefault((e.name, id(e.svc)), []).append(e)
        for (name, _svc_id), entries in groups.items():
            svc = entries[0].svc
            bodies = [e.body for e in entries]
            try:
                resps = self._run_batch(name, svc, bodies)
            except Exception:                       # noqa: BLE001
                with self._cond:
                    self.batch_errors += 1
                METRICS.counter("serving.batch_errors").inc()
                resps = [None] * len(entries)
            if self.config.oracle:
                self._oracle_check(name, svc, entries, resps)
            with self._cond:
                for e, r in zip(entries, resps):
                    if r is not None:
                        self.batched_served += 1
                    else:
                        self.declined += 1
            for e, r in zip(entries, resps):
                e.resp = r
                e.state = _DONE
                e.done.set()
            METRICS.counter("serving.batched_served").inc(
                sum(1 for r in resps if r is not None))
            METRICS.counter("serving.declined").inc(
                sum(1 for r in resps if r is None))

    def _run_batch(self, name: str, svc, bodies: List[dict]) -> list:
        """One batched program invocation over the pending bodies: the
        SPMD mesh first (multi-shard), then the fastpath's grouped kernel
        launches for the remainder. Entries still None take the host loop
        on their own request threads — which also parallelizes the
        host-side fallback work instead of serializing it here."""
        node = self.node
        resps: List[Optional[dict]] = [None] * len(bodies)
        if node.mesh_service is not None:
            mesh = node.mesh_service.try_msearch(name, svc, bodies)
            if mesh is not None:
                resps = list(mesh)
        todo = [i for i, r in enumerate(resps) if r is None]
        # kernel batching only when there is something to coalesce: a
        # LONE mesh-declined body must take exactly the scheduler-off
        # path (host loop, incl. its shard-view/pruned rung attribution)
        # — coalescing may change execution only when it actually fuses
        if self.config.kernel_batching and len(todo) >= 2:
            from ..search.executor import msearch_batched
            batched = msearch_batched(svc.searchers,
                                      [bodies[i] for i in todo],
                                      index_name=name)
            if batched is not None:
                for i, r in zip(todo, batched):
                    if resps[i] is None:
                        resps[i] = r
        return resps

    # ---------------- degraded / oracle paths ----------------

    def _direct(self, name: str, svc, body: dict):
        """Direct per-request execution — exactly what Node.search does
        with the scheduler off (mesh attempt; host loop stays with the
        caller, which treats None as a decline)."""
        if self.node.mesh_service is not None:
            return self.node.mesh_service.try_search(name, svc, body)
        return None

    def _oracle_reference(self, name: str, svc, body: dict):
        """The direct-execution equivalent of a SERVED batched body:
        the mesh when it serves the shape, else a batch-of-one kernel
        launch (probing the grouped kernel path's batch-size
        invariance) — mirroring the two stages _run_batch composes."""
        if self.node.mesh_service is not None:
            direct = self.node.mesh_service.try_search(name, svc, body)
            if direct is not None:
                return direct
        from ..search.executor import msearch_batched
        single = msearch_batched(svc.searchers, [body], index_name=name)
        return single[0] if single is not None else None

    @staticmethod
    def _normalize(resp) -> Optional[str]:
        if resp is None:
            return None
        out = {k: v for k, v in resp.items() if k != "took"}
        return _json.dumps(out, sort_keys=True, default=repr)

    def _oracle_check(self, name: str, svc, entries: List[_Pending],
                      resps: list) -> None:
        """Run every body through the direct path too and compare (modulo
        wall-clock `took`). Dispatch counters run twice in this mode — it
        exists to prove the identical-results contract, not to serve."""
        for e, r in zip(entries, resps):
            if r is None:
                # declined (or error-degraded): the caller's host loop
                # serves it — nothing BATCHED was produced to verify
                continue
            oracle_body = _copy.deepcopy(e.body)
            oracle_body.pop("_mesh_declined", None)
            try:
                direct = self._oracle_reference(name, svc, oracle_body)
                match = self._normalize(r) == self._normalize(direct)
            except Exception:                       # noqa: BLE001
                match = False
            with self._cond:
                self.oracle_checks += 1
                if not match:
                    self.oracle_mismatches += 1
                    self.last_oracle_mismatch = (
                        f"index [{name}] body "
                        f"{_json.dumps(e.body, default=repr)[:400]}: "
                        f"batched != direct")
            METRICS.counter("serving.oracle_checks").inc()
            if not match:
                METRICS.counter("serving.oracle_mismatches").inc()

    # ---------------- lifecycle + stats ----------------

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until the pending queue is empty WITHOUT closing the
        scheduler (a transport shutting down must not end the Node-wide
        scheduler's life — another transport, or the dict API, keeps
        coalescing). Returns False when the timeout expired first."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def close(self, drain: bool = True) -> None:
        """Stop the dispatcher. With drain=True pending entries are
        flushed one last time; without it they degrade to direct
        execution via the request-thread timeout path."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None and drain:
            t.join(timeout=5.0)

    def stats(self) -> dict:
        with self._cond:
            depth = self._pending
            out = {
                "enabled": self.enabled,
                "queue_depth": depth,
                "queue_cap": self.config.queue_cap,
                "max_batch": self.config.max_batch,
                "max_wait_us": self.config.max_wait_us,
                "submitted": self.submitted,
                "batched_served": self.batched_served,
                "declined": self.declined,
                "bypassed": self.bypassed,
                "rejected": self.rejected,
                "cancelled_dropped": self.cancelled_dropped,
                "direct_fallbacks": self.direct_fallbacks,
                "batch_errors": self.batch_errors,
                "flushes": self.flushes,
                "flush_reasons": dict(self.flush_reasons),
                "lanes": {lane: {"flushed": self.lane_flushed[lane]}
                          for lane in LANES},
                "oracle": {"enabled": self.config.oracle,
                           "checks": self.oracle_checks,
                           "mismatches": self.oracle_mismatches},
            }
        out["batch_size"] = self._local.percentiles("serving.batch_size")
        out["queue_wait_ms"] = self._local.percentiles("serving.queue_wait")
        return out

    def note_bypass(self) -> None:
        with self._cond:
            self.bypassed += 1
        METRICS.counter("serving.bypassed").inc()
