"""Serving scheduler: cross-request dynamic batching with deadline-aware
flush and priority lanes (docs/SERVING.md).

The runtime's device programs batch over the QUERY axis (mesh
`try_msearch` groups, fastpath `msearch_batched` kernel grids), but only
queries arriving inside one `_msearch` body ever shared a launch —
concurrent independent searches from `ThreadingHTTPServer` threads each
paid their own dispatch and serialized on the chip. This scheduler sits
between the REST layer and `MeshSearchService`: eligible searches enqueue
into a bounded two-lane queue, and a single dispatcher thread flushes the
pending set as ONE batched program invocation when either `max_batch`
requests are waiting or the oldest has waited `max_wait_us` (whichever
first). Per-request futures carry results, errors and timeouts back to
the submitting HTTP threads.

Contracts:

- **Bit-identical results.** A flushed batch rides the exact query-axis
  batching `_msearch` already uses (`MeshSearchService.try_msearch`,
  `executor.msearch_batched`); per-query scoring is independent of batch
  composition (pow2 query padding, per-row f32 accumulation, per-query
  top-k merge), so a coalesced search serves the same pages, scores and
  tie-breaks as a direct one — the f32 tie-serve contract from
  docs/FASTPATH.md is untouched. `SchedulerConfig.oracle` (env
  `OPENSEARCH_TPU_SCHED_ORACLE=1`) re-runs every coalesced body through
  the direct path on the dispatcher thread and counts mismatches.
- **Graceful degradation.** Non-coalescable shapes bypass the queue
  unchanged (`accepts`); a closed scheduler, an entry still queued at
  the request timeout (wedged dispatcher), or a batch execution error
  falls back to direct per-request execution (an entry already claimed
  into an in-flight batch is waited out, not duplicated) — the scheduler
  can only ever make an eligible request *batched*, never make it fail.
- **Cancellation.** A cancelled `utils/tasks.py` task is dropped from the
  pending set before launch: `Task.on_cancel` wakes the scheduler, which
  resolves the entry with `TaskCancelledException` without dispatching it.
- **Admission.** The queue is bounded (`queue_cap`); a full queue rejects
  with `PressureRejectedException` (HTTP 429) and is counted by
  `SearchBackpressureService` — concurrency converts to backpressure, not
  unbounded growth.

Lanes: requests carry a lane from their `utils/wlm.py` workload group
("interactive" default; groups configured with `lane: "batch"`, and
scroll-initiating searches, ride the batch lane). At flush time the
interactive lane preempts the batch lane: interactive entries fill the
batch first, batch/scroll entries only take the leftover slots.

All waiting uses `threading.Condition` / `threading.Event` — no sleep
polling (oslint OSL503, docs/STATIC_ANALYSIS.md).

**Pipelined dispatch** (this PR): the dispatch path is split into an
explicit LAUNCH stage and a FETCH/RENDER stage connected by
`search/launch.py` LaunchHandles. The dispatcher thread now only
assembles and *launches* (program invocation under
`MeshSearchService._dispatch_lock`, released before any sync); completed
launches enter a bounded in-flight window and a completion worker thread
performs the device sync, oracle re-check, response rendering and future
resolution — so host assembly of batch N+1 overlaps device execution of
batch N. `SchedulerConfig.pipeline_depth` bounds the window
(`OPENSEARCH_TPU_PIPELINE_DEPTH`, default 2); depth 1 is byte-for-byte
the old synchronous dispatcher (and the `JAX_PLATFORMS=cpu` oracle
baseline). Degradation ladders extend to the new stage: a wedged
completion worker abandons the claimed entry to direct execution on the
request thread after a second `request_timeout_s`, and a task cancelled
after launch but before fetch resolves immediately (the batch's result
for it is discarded). Telemetry: `serving.inflight_depth` gauge,
`serving.launch_to_fetch` histogram, and a launch/fetch stage overlap
ratio in `_nodes/stats` "serving" -> "pipeline" and `/_metrics`.
"""

from __future__ import annotations

import copy as _copy
import json as _json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..obs import flight_recorder as _fr
from ..utils.metrics import METRICS, MetricsRegistry
from ..utils.tasks import TaskCancelledException
from ..utils.wlm import PressureRejectedException

LANES = ("interactive", "batch")

# body keys MeshSearchService._eligible statically declines — queueing
# these shapes would add latency for a guaranteed host-loop outcome, so
# they bypass the scheduler unchanged (the decline still happens at the
# same place it does today, with the same attribution).
# `knn` is NOT in this list (ISSUE 15): pure-knn bodies are first-class
# scheduler citizens — they enqueue, ride the lanes/admission/429 path
# (so the remediator can shed vector floods), and coalesce through the
# vmapped batched-knn program (executor._launch_knn_segment)
_BYPASS_KEYS = ("rescore", "min_score", "profile", "collapse",
                "suggest", "search_after", "highlight", "script_fields",
                # budgeted bodies need the deadline-AWARE executor: only
                # the host shard loop stops between segment programs
                # (terminate_after) / checks the deadline — the batched
                # mesh/kernel launches are deadline-blind, so a `timeout`
                # body coalesced into a batch could blow its budget
                # inside one launch with no partial-results exit. The
                # entry.wait_s derivation below still serves requests
                # whose deadline arrives AMBIENTLY (hop-propagated
                # deadline_ctx, no body timeout — ROADMAP item 2's
                # per-node schedulers)
                "terminate_after", "timeout")

# entry states (transitions under the scheduler condition lock)
_QUEUED, _CLAIMED, _DONE, _ABANDONED = "queued", "claimed", "done", "abandoned"


class SchedulerConfig:
    """Tuning knobs (env defaults; see docs/SERVING.md for the
    latency/throughput trade-off each one moves)."""

    def __init__(self, max_batch: Optional[int] = None,
                 max_wait_us: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 oracle: Optional[bool] = None,
                 kernel_batching: bool = True,
                 request_timeout_s: float = 30.0,
                 idle_timeout_s: float = 5.0,
                 pipeline_depth: Optional[int] = None):
        env = os.environ
        self.max_batch = int(max_batch if max_batch is not None
                             else env.get("OPENSEARCH_TPU_SCHED_MAX_BATCH",
                                          32))
        self.max_wait_us = int(max_wait_us if max_wait_us is not None
                               else env.get(
                                   "OPENSEARCH_TPU_SCHED_MAX_WAIT_US", 1000))
        self.queue_cap = int(queue_cap if queue_cap is not None
                             else env.get("OPENSEARCH_TPU_SCHED_QUEUE_CAP",
                                          256))
        if oracle is None:
            oracle = env.get("OPENSEARCH_TPU_SCHED_ORACLE",
                             "") not in ("", "0")
        self.oracle = bool(oracle)
        # also coalesce mesh-declined / mesh-less bodies through the
        # fastpath's grouped kernel launches (executor.msearch_batched)
        self.kernel_batching = bool(kernel_batching)
        self.request_timeout_s = float(request_timeout_s)
        self.idle_timeout_s = float(idle_timeout_s)
        # bounded in-flight window for pipelined dispatch: at most this
        # many launched-but-unfetched batches, so the device queue can't
        # grow without bound. Depth 1 == the synchronous dispatcher the
        # scheduler shipped with (launch+fetch on one thread) — the
        # JAX_PLATFORMS=cpu oracle baseline for pipeline parity.
        self.pipeline_depth = int(
            pipeline_depth if pipeline_depth is not None
            else env.get("OPENSEARCH_TPU_PIPELINE_DEPTH", 2))
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        if self.queue_cap < 1:
            raise ValueError("queue_cap must be >= 1")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")


class _Pending:
    __slots__ = ("name", "svc", "body", "lane", "task", "enq", "done",
                 "resp", "error", "state", "tl", "wait_s")

    def __init__(self, name: str, svc, body: dict, lane: str, task):
        self.name = name
        self.svc = svc
        self.body = body
        self.lane = lane
        self.task = task
        self.enq = time.monotonic()
        self.done = threading.Event()
        self.resp = None            # response dict, or None (-> host loop)
        self.error: Optional[BaseException] = None
        self.state = _QUEUED
        # flight-recorder timeline of the submitting request: the
        # dispatcher/completion threads have no ambient timeline, so the
        # id rides the entry explicitly (0 = recorder disabled)
        self.tl = 0
        # scheduler deadline, derived from the request's remaining
        # budget at enqueue (deadline ladder, docs/RESILIENCE.md); None
        # = no ambient deadline, wait the configured request timeout
        self.wait_s: Optional[float] = None

    def _stage(self, stage) -> None:
        """Mark the live serving stage on the request's task (surfaced by
        `_tasks`; None = left the scheduler); no-op for task-less
        entries."""
        t = self.task
        if t is not None and hasattr(t, "set_stage"):
            t.set_stage(stage)


class _StageMeter:
    """Interval-union accounting for the launch and fetch stages: per-kind
    busy seconds plus the union wall during which ANY stage was active.
    overlap = launch_s + fetch_s - union_s is the wall the two stages ran
    concurrently — the host-side witness that device execution (the fetch
    stage blocks on it) overlapped host assembly. At pipeline depth 1 the
    stages share one thread, so the overlap is identically zero."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = 0
        self._mark = 0.0
        self.stage_s = {"launch": 0.0, "fetch": 0.0}
        self.union_s = 0.0

    @contextmanager
    def stage(self, kind: str):
        t0 = time.monotonic()
        with self._lock:
            if self._active == 0:
                self._mark = t0
            self._active += 1
        try:
            yield
        finally:
            t1 = time.monotonic()
            with self._lock:
                self._active -= 1
                self.stage_s[kind] += t1 - t0
                if self._active == 0:
                    self.union_s += t1 - self._mark
                ratio = self._ratio_locked()
            METRICS.gauge("serving.overlap_ratio").set(round(ratio, 4))

    def _ratio_locked(self) -> float:
        total = self.stage_s["launch"] + self.stage_s["fetch"]
        if self.union_s <= 0.0:
            return 0.0
        return max(total - self.union_s, 0.0) / self.union_s

    def snapshot(self) -> dict:
        with self._lock:
            total = self.stage_s["launch"] + self.stage_s["fetch"]
            return {
                "launch_s": round(self.stage_s["launch"], 4),
                "fetch_s": round(self.stage_s["fetch"], 4),
                "union_s": round(self.union_s, 4),
                "overlap_s": round(max(total - self.union_s, 0.0), 4),
                "overlap_ratio": round(self._ratio_locked(), 4),
            }


class _InFlight:
    """One launched batch parked in the in-flight window: per-(index,
    service) groups, each holding its claimed entries and the launch
    handles the completion worker will fetch."""

    __slots__ = ("groups", "launched_at")

    def __init__(self, groups: list):
        # [(name, svc, entries, bodies, handles-or-None, launch_error)]
        self.groups = groups
        self.launched_at = time.monotonic()

    def unresolved(self):
        for _name, _svc, entries, _bodies, _handles, _err in self.groups:
            for e in entries:
                if not e.done.is_set():
                    yield e


class ServingScheduler:
    """One per Node. `execute()` is the only entry point the search path
    uses; everything else is dispatcher machinery and telemetry."""

    def __init__(self, node, config: Optional[SchedulerConfig] = None,
                 enabled: Optional[bool] = None):
        self.node = node
        self.config = config or SchedulerConfig()
        if enabled is None:
            flag = os.environ.get("OPENSEARCH_TPU_SCHED")
            if flag is not None:
                enabled = flag not in ("", "0")
            else:
                # default: on whenever there is a device batching substrate
                # worth coalescing for (the SPMD mesh); single-chip nodes
                # opt in with OPENSEARCH_TPU_SCHED=1 (kernel batching)
                enabled = node.mesh_service is not None
        self.enabled = bool(enabled)
        # the one condition every enqueue/flush/close handshake rides;
        # its only committed downstream acquisition is the metrics
        # registry (lock_order.json) — never call out to RPC/device
        # work while holding it (OSL702)
        self._cond = threading.Condition()
        self._lanes: Dict[str, deque] = {lane: deque() for lane in LANES}
        self._pending = 0
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # pipelined dispatch: launched-but-unfetched batches (bounded by
        # config.pipeline_depth; the head entry stays in the deque while
        # the completion worker fetches it, so the window counts every
        # batch the device still owes results for)
        self._inflight: deque = deque()
        self._cthread: Optional[threading.Thread] = None
        self._meter = _StageMeter()
        self._inflight_peak = 0
        self.launched_batches = 0
        self.completed_batches = 0
        self.cancelled_inflight = 0     # cancelled after launch, pre-fetch
        self.completion_abandoned = 0   # wedged completion -> ran direct
        # counters (mutated under self._cond; mirrored into METRICS)
        self.submitted = 0
        self.batched_served = 0     # resolved with a batched response
        self.declined = 0           # resolved None -> host loop
        self.bypassed = 0           # accepts() said no -> direct path
        self.rejected = 0           # queue full -> 429
        self.cancelled_dropped = 0  # dropped before launch
        self.direct_fallbacks = 0   # degraded mode: ran direct
        self.batch_errors = 0
        self.flushes = 0
        self.flush_reasons = {"size": 0, "deadline": 0, "drain": 0}
        self.lane_flushed = {lane: 0 for lane in LANES}
        self.oracle_checks = 0
        self.oracle_mismatches = 0
        self.last_oracle_mismatch: Optional[str] = None
        # per-instance histogram mirror: the process-global METRICS
        # registry feeds /_metrics, but THIS node's `_nodes/stats` block
        # must not blend in a co-resident node's flushes (remote-cluster
        # peers, multi-node tests share the process)
        self._local = MetricsRegistry()

    # ---------------- eligibility ----------------

    def accepts(self, body) -> bool:
        """Cheap coalescability screen. Permissive by design: anything it
        lets through still goes through the mesh/fastpath's own
        eligibility and falls back to the host loop on decline; this only
        spares statically-hopeless shapes the queue wait."""
        if not isinstance(body, dict):
            return False
        for k in _BYPASS_KEYS:
            if body.get(k) is None:
                continue
            if k == "timeout":
                # only a LIVE budget forces the host loop; the reference
                # no-timeout sentinel (-1 -> no deadline) keeps batching
                from ..utils.deadline import parse_timeout_s
                try:
                    if parse_timeout_s(body["timeout"]) is None:
                        continue
                except ValueError:
                    pass             # junk -> host loop raises the 400
            return False
        if body.get("explain") == "device_plan":
            # the device-plan cost view needs the requesting thread's own
            # cost accumulator (obs/query_cost.py) — a coalesced launch
            # on the dispatcher thread can't attribute per-request
            return False
        q = body.get("query")
        if q is not None and not isinstance(q, dict):
            return False
        return True

    # ---------------- admission state ----------------

    def _effective_cap(self) -> int:
        """The live admission bound: queue_cap, contracted by the
        remediation actuator's admission factor while a
        tighten_admission action holds (never below 1)."""
        cap = self.config.queue_cap
        rem = getattr(self.node, "remediation", None)
        if rem is not None and rem.tightened:
            cap = max(1, int(cap * rem.queue_factor()))
        return cap

    def _retry_after_s(self, depth: int) -> float:
        """The honest `Retry-After` hint for a queue-full 429, derived
        from the admission state the client just hit: the estimated
        drain time of the current queue (batches needed x the flush
        deadline), floored so a zero-wait config still asks for a
        beat of backoff."""
        per_flush_s = max(self.config.max_wait_us / 1e6, 0.01)
        batches = max((depth + self.config.max_batch - 1)
                      // self.config.max_batch, 1)
        return max(batches * per_flush_s, 0.05)

    # ---------------- request side ----------------

    def execute(self, name: str, svc, body: dict, task=None,
                lane: str = "interactive"):
        """Coalesce one eligible search into the next flushed batch.
        Returns the batched response dict, or None when the batch path
        declined the body (caller runs the host shard loop — identical to
        a direct mesh decline). Raises PressureRejectedException when the
        queue is full and TaskCancelledException when the request's task
        was cancelled before launch."""
        if lane not in self._lanes:
            lane = "interactive"
        entry = _Pending(name, svc, body, lane, task)
        if _fr.RECORDER.enabled:
            entry.tl = _fr.current()
        from ..utils import deadline as _ddl
        _dl = _ddl.current()
        if _dl is not None:
            # the scheduler's own deadline derives from what is LEFT of
            # the request budget at enqueue — queue wait spends from the
            # same clock as everything downstream
            entry.wait_s = max(min(self.config.request_timeout_s,
                                   _dl.remaining_s()), 0.0)
        # ONE critical section for closed-check, admission, dispatcher
        # liveness and enqueue: the dispatcher's idle-exit decision runs
        # under the same condition, so an entry can never land in the
        # queue with no dispatcher alive and none restarted
        rejected_depth = None
        closed = False
        # admission cap: the configured bound, contracted while a
        # remediation tighten_admission action is engaged
        # (serving/remediator.py) — 429s fire earlier under active
        # remediation, and relax to exactly queue_cap on release
        cap = self._effective_cap()
        with self._cond:
            if self._closed:
                self.direct_fallbacks += 1
                METRICS.counter("serving.direct_fallbacks").inc()
                closed = True
            elif self._pending >= cap:
                self.rejected += 1
                METRICS.counter("serving.rejected").inc()
                # per-lane mirror: ONE consistent rejection name across
                # every admission layer (wlm, scheduler, remediation) —
                # the SLO engine's rejection-rate objectives and the
                # remediation loop both window serving.lane.*.rejected
                METRICS.counter(f"serving.lane.{lane}.rejected").inc()
                self.node.search_backpressure.note_queue_rejection()
                rejected_depth = self._pending
            else:
                if not self._dispatcher_alive():
                    self._start_dispatcher()
                self.submitted += 1
                METRICS.counter("serving.submitted").inc()
                METRICS.counter(f"serving.lane.{lane}.submitted").inc()
                self._lanes[lane].append(entry)
                self._pending += 1
                METRICS.gauge("serving.queue_depth").set(self._pending)
                entry._stage("queued")
                if _fr.RECORDER.enabled and entry.tl:
                    _fr.RECORDER.record(entry.tl, "sched.enqueue",
                                        lane=lane, depth=self._pending)
                self._cond.notify_all()
        if rejected_depth is not None:
            # attribute the 429 to the request's query shape: the
            # insights engine counts rejections per fingerprint, the
            # admission-threshold remediation input (obs/insights.py)
            from ..obs import insights as _ins
            _ins.note_rejection_source("scheduler")
            # event + burst detection OUTSIDE the scheduler lock: a burst
            # trigger freezes a dump bundle, and that scan must not stall
            # every other submit/flush/cancel on _cond
            if _fr.RECORDER.enabled:
                if entry.tl:
                    _fr.RECORDER.record(entry.tl, "sched.reject",
                                        pending=rejected_depth,
                                        cap=cap)
                _fr.RECORDER.note_rejection(entry.tl)
            raise PressureRejectedException(
                f"serving scheduler queue full "
                f"({rejected_depth}/{cap} pending); "
                f"rejecting search",
                retry_after_s=self._retry_after_s(rejected_depth),
                source="scheduler")
        if closed:
            if _fr.RECORDER.enabled and entry.tl:
                _fr.RECORDER.record(entry.tl, "sched.degrade",
                                    why="closed")
            return self._direct(name, svc, body)
        if task is not None and hasattr(task, "on_cancel"):
            # wake + drop the entry the moment its task is cancelled (the
            # flush assembly re-checks as a backstop)
            task.on_cancel(lambda _t, e=entry: self._drop_cancelled(e))
        return self._await(entry)

    def _await(self, entry: _Pending):
        wait1 = (entry.wait_s if entry.wait_s is not None
                 else self.config.request_timeout_s)
        deadline_cut = entry.wait_s is not None \
            and entry.wait_s < self.config.request_timeout_s
        if not entry.done.wait(wait1):
            with self._cond:
                if entry.state == _QUEUED:
                    # scheduler wedged with the entry still queued: pull it
                    # and degrade to direct execution on this thread
                    try:
                        self._lanes[entry.lane].remove(entry)
                        self._pending -= 1
                        METRICS.gauge("serving.queue_depth").set(
                            self._pending)
                        self._cond.notify_all()
                    except ValueError:
                        pass
                    entry.state = _ABANDONED
                    self.direct_fallbacks += 1
                    METRICS.counter("serving.direct_fallbacks").inc()
            if entry.state == _ABANDONED:
                if deadline_cut:
                    # the REQUEST's budget (shorter than the scheduler
                    # timeout) ran out while queued — not a wedge, no
                    # dump: degrade to direct execution, which the
                    # executor's own deadline check turns into an
                    # immediate honest timed_out partial page
                    if _fr.RECORDER.enabled and entry.tl:
                        _fr.RECORDER.record(
                            entry.tl, "sched.degrade",
                            why="request_deadline",
                            waited_ms=round(
                                (time.monotonic() - entry.enq) * 1000.0,
                                3))
                    entry._stage(None)
                    return self._direct(entry.name, entry.svc, entry.body)
                # the request missed its deadline while STILL QUEUED — the
                # dispatcher is wedged or starved. Freeze the timeline
                # before degrading: this is exactly the after-the-fact
                # forensic moment the flight recorder exists for
                if _fr.RECORDER.enabled and entry.tl:
                    _fr.RECORDER.record(
                        entry.tl, "sched.degrade", why="deadline_miss",
                        waited_ms=round(
                            (time.monotonic() - entry.enq) * 1000.0, 3))
                    _fr.RECORDER.trigger(
                        "deadline_miss", [entry.tl],
                        note=f"entry still queued after "
                             f"{self.config.request_timeout_s}s")
                entry._stage(None)
                return self._direct(entry.name, entry.svc, entry.body)
            # claimed: the batch is in flight on the device. Duplicating
            # it immediately would be wasteful, so give the completion
            # stage one more request_timeout — but a WEDGED completion
            # worker (hung fetch) must not hold the request hostage:
            # abandon the entry and run direct on this thread (the batch
            # result for it is discarded by the state guard).
            if not entry.done.wait(self.config.request_timeout_s):
                with self._cond:
                    if entry.state == _CLAIMED:
                        entry.state = _ABANDONED
                        self.direct_fallbacks += 1
                        self.completion_abandoned += 1
                        METRICS.counter("serving.direct_fallbacks").inc()
                        METRICS.counter(
                            "serving.completion_abandoned").inc()
                if entry.state == _ABANDONED:
                    # launched but never fetched: the completion stage is
                    # wedged. Dump the timeline (it already holds the
                    # flush's batch peers and the launch boundary) before
                    # running direct on this thread
                    if _fr.RECORDER.enabled and entry.tl:
                        _fr.RECORDER.record(
                            entry.tl, "sched.degrade",
                            why="completion_wedge",
                            waited_ms=round(
                                (time.monotonic() - entry.enq) * 1000.0,
                                3))
                        _fr.RECORDER.trigger(
                            "completion_wedge", [entry.tl],
                            note=f"claimed entry unresolved after "
                                 f"2x{self.config.request_timeout_s}s")
                    entry._stage(None)
                    return self._direct(entry.name, entry.svc, entry.body)
                entry.done.wait()     # resolved racing with our timeout
        if entry.error is not None:
            raise entry.error
        return entry.resp

    def _drop_cancelled(self, entry: _Pending) -> None:
        with self._cond:
            if entry.state == _CLAIMED and not entry.done.is_set():
                # already launched, not yet fetched: the device work can't
                # be recalled, but the caller need not wait for it — mark
                # the entry resolved-with-cancellation now; the completion
                # stage's state guard discards the batch result for it
                entry.state = _DONE
                entry.error = TaskCancelledException(
                    f"task [{getattr(entry.task, 'id', '?')}] cancelled "
                    f"after batch launch, before fetch: "
                    f"{getattr(entry.task, 'cancel_reason', None)}")
                self.cancelled_inflight += 1
                METRICS.counter("serving.cancelled_inflight").inc()
                if _fr.RECORDER.enabled and entry.tl:
                    _fr.RECORDER.record(entry.tl, "sched.cancel",
                                        where="inflight")
                entry._stage(None)
                entry.done.set()
                return
            if entry.state != _QUEUED:
                return
            try:
                self._lanes[entry.lane].remove(entry)
                self._pending -= 1
                METRICS.gauge("serving.queue_depth").set(self._pending)
                self._cond.notify_all()      # wake drain() waiters
            except ValueError:
                return
            self._resolve_cancelled(entry)

    def _resolve_cancelled(self, entry: _Pending) -> None:
        entry.state = _DONE
        entry.error = TaskCancelledException(
            f"task [{getattr(entry.task, 'id', '?')}] cancelled while "
            f"queued for batch dispatch: "
            f"{getattr(entry.task, 'cancel_reason', None)}")
        self.cancelled_dropped += 1
        METRICS.counter("serving.cancelled_dropped").inc()
        if _fr.RECORDER.enabled and entry.tl:
            _fr.RECORDER.record(entry.tl, "sched.cancel", where="queued")
        entry._stage(None)
        entry.done.set()

    # ---------------- dispatcher side ----------------

    def _dispatcher_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _start_dispatcher(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="ostpu-serving-dispatcher",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cond:
                # idle wait: exit after idle_timeout so test suites that
                # spin up hundreds of Nodes don't accumulate parked
                # threads; submit() restarts the dispatcher lazily
                while self._pending == 0 and not self._closed:
                    if not self._cond.wait(self.config.idle_timeout_s) \
                            and self._pending == 0:
                        if self._thread is me:
                            self._thread = None
                        return
                if self._closed and self._pending == 0:
                    return
                reason = self._wait_flush()
                if self._pending == 0:
                    continue
                # in-flight window backpressure: launching past the
                # window would let the device queue grow without bound —
                # wait for the completion worker to retire a batch (the
                # queue keeps admitting, and batching, meanwhile)
                while len(self._inflight) >= self.config.pipeline_depth \
                        and not self._closed:
                    self._cond.wait(self.config.idle_timeout_s)
                batch = self._assemble(reason)
            if not batch:
                continue
            try:
                if self.config.pipeline_depth <= 1:
                    # depth 1 == the pre-pipeline dispatcher: launch +
                    # fetch + render synchronously on this thread
                    with self._meter.stage("launch"):
                        self._dispatch(batch)
                else:
                    with self._meter.stage("launch"):
                        item = self._launch_stage(batch)
                    self._enqueue_inflight(item)
            except BaseException:           # noqa: BLE001
                # never strand claimed entries: whatever killed the
                # dispatch, every waiter degrades to the host loop
                for e in batch:
                    if not e.done.is_set():
                        e.resp = None
                        e.state = _DONE
                        e.done.set()
                raise

    def _wait_flush(self) -> str:
        """Block (under the cond) until the flush policy fires: size
        (max_batch pending) or deadline (oldest waited max_wait_us)."""
        max_wait_s = self.config.max_wait_us / 1e6
        while True:
            if self._closed:
                return "drain"
            if self._pending >= self.config.max_batch:
                return "size"
            heads = [self._lanes[lane][0].enq for lane in LANES
                     if self._lanes[lane]]
            oldest = min(heads) if heads else None
            if oldest is None:
                return "deadline"     # emptied while we slept
            remaining = max_wait_s - (time.monotonic() - oldest)
            if remaining <= 0:
                return "deadline"
            self._cond.wait(remaining)

    def _assemble(self, reason: str) -> List[_Pending]:
        """Pop up to max_batch entries — interactive lane first (FIFO
        within a lane, batch/scroll lane fills the leftover slots) — and
        drop entries whose task was cancelled while queued. One slot is
        reserved for the batch lane whenever it has waiters: preemption
        means the interactive lane goes first, not that sustained
        interactive saturation starves scroll traffic into its request
        timeout."""
        batch: List[_Pending] = []
        for lane in LANES:                  # interactive preempts batch
            cap = self.config.max_batch
            if lane == "interactive" and self._lanes["batch"] and cap > 1:
                cap -= 1                    # starvation guard
            q = self._lanes[lane]
            while q and len(batch) < cap:
                entry = q.popleft()
                self._pending -= 1
                if entry.task is not None and \
                        getattr(entry.task, "cancelled", False):
                    self._resolve_cancelled(entry)
                    continue
                entry.state = _CLAIMED
                batch.append(entry)
                self.lane_flushed[lane] += 1
                METRICS.counter(f"serving.lane.{lane}.flushed").inc()
        METRICS.gauge("serving.queue_depth").set(self._pending)
        self._cond.notify_all()          # wake drain() waiters
        if batch:
            self.flushes += 1
            self.flush_reasons[reason] = \
                self.flush_reasons.get(reason, 0) + 1
            METRICS.counter(f"serving.flush.{reason}").inc()
            METRICS.histogram("serving.batch_size").record(len(batch))
            self._local.histogram("serving.batch_size").record(len(batch))
            now = time.monotonic()
            for e in batch:
                wait_ms = (now - e.enq) * 1000.0
                METRICS.histogram("serving.queue_wait").record(wait_ms)
                self._local.histogram("serving.queue_wait").record(wait_ms)
            if _fr.RECORDER.enabled:
                # batch peers: every timeline in this flush carries the
                # full co-batched set, so a dump of ONE wedged request
                # names the requests that shared its launch
                peers = [e.tl for e in batch if e.tl]
                for e in batch:
                    if e.tl:
                        _fr.RECORDER.record(
                            e.tl, "sched.flush", reason=reason,
                            size=len(batch), lane=e.lane,
                            queue_wait_ms=round(
                                (now - e.enq) * 1000.0, 3),
                            peers=[p for p in peers if p != e.tl])
        return batch

    def _dispatch(self, batch: List[_Pending]) -> None:
        """Depth-1 synchronous dispatch: run the flushed batch grouped by
        index and hand every entry its result on this thread. Never
        raises: a failed group degrades its entries to the host loop
        (resp None). Stage marks (launched/fetching/rendering) and the
        per-entry launch/fetch boundary events mirror the pipelined
        path's, so `_tasks` and timelines read identically at any depth."""
        for (name, svc, entries, bodies) in self._group(batch):
            try:
                handles = self._launch_group(name, svc, bodies)
                err = False
            except Exception:                       # noqa: BLE001
                handles = None
                err = True
            for e in entries:
                if e.state == _CLAIMED:   # not cancelled/abandoned since
                    e._stage("launched")
            self._record_launch(entries, handles, err)
            if err:
                with self._cond:
                    self.batch_errors += 1
                METRICS.counter("serving.batch_errors").inc()
                resps = [None] * len(entries)
            else:
                for e in entries:
                    if e.state == _CLAIMED:
                        e._stage("fetching")
                try:
                    resps = self._finish_group(name, svc, bodies, handles)
                except Exception:                   # noqa: BLE001
                    with self._cond:
                        self.batch_errors += 1
                    METRICS.counter("serving.batch_errors").inc()
                    resps = [None] * len(entries)
            for e in entries:
                if e.state == _CLAIMED:
                    e._stage("rendering")
            if self.config.oracle:
                self._oracle_check(name, svc, entries, resps)
            self._resolve_entries(entries, resps)

    def _record_launch(self, entries: List[_Pending], handles,
                       err: bool) -> None:
        """Per-entry launch-boundary events. The dispatcher thread has no
        ambient timeline, so the ids ride the entries; `handle.info`
        carries the mesh's launch forensics (dispatch-lock wait, new
        program compiles)."""
        if not _fr.RECORDER.enabled:
            return
        fields: dict = {"path": "none"}
        if handles is not None:
            mesh_handle, kernel_handle = handles
            h = mesh_handle if mesh_handle is not None else kernel_handle
            if h is not None:
                fields["path"] = ("mesh" if mesh_handle is not None
                                  else "kernel")
                if getattr(h, "info", None):
                    fields.update(h.info)
        if err:
            fields["error"] = True
        for e in entries:
            if e.tl:
                _fr.RECORDER.record(e.tl, "sched.launch", **fields)

    @staticmethod
    def _group(batch: List[_Pending]) -> list:
        """[(name, svc, entries, bodies)] grouped by (name, service
        identity), not name alone: two entries can hold DIFFERENT
        IndexService snapshots for one name (index deleted + recreated
        between their enqueues) and each must be served from its own
        service, like the direct path would.

        Bodies are top-level COPIES: the batch paths insert top-level
        keys (`_mesh_declined`, `_index_name`) and iterate the dict, and
        an entry abandoned to direct execution (completion wedge) has its
        ORIGINAL body concurrently read by the request thread — sharing
        the dict would let a late fetch mutate it mid-iteration. Inner
        structures are read-only on both sides and stay shared."""
        groups: Dict[tuple, List[_Pending]] = {}
        for e in batch:
            groups.setdefault((e.name, id(e.svc)), []).append(e)
        return [(name, entries[0].svc, entries,
                 [dict(e.body) if isinstance(e.body, dict) else e.body
                  for e in entries])
                for (name, _sid), entries in groups.items()]

    def _resolve_entries(self, entries: List[_Pending],
                         resps: list) -> None:
        """Hand each claimed entry its result. The state guard makes
        resolution race-free against the in-flight degradation paths: an
        entry cancelled after launch or abandoned to direct execution by
        a wedged completion stage is NOT overwritten — its batch result
        is discarded."""
        served = declined = 0
        for e, r in zip(entries, resps):
            with self._cond:
                if e.state != _CLAIMED:
                    continue
                e.state = _DONE
                if r is not None:
                    self.batched_served += 1
                    served += 1
                else:
                    self.declined += 1
                    declined += 1
            e.resp = r
            if _fr.RECORDER.enabled and e.tl:
                _fr.RECORDER.record(e.tl, "sched.resolve",
                                    served=r is not None)
            e._stage(None)
            e.done.set()
        METRICS.counter("serving.batched_served").inc(served)
        METRICS.counter("serving.declined").inc(declined)

    # ---------------- pipelined dispatch ----------------

    def _launch_group(self, name: str, svc, bodies: List[dict]) -> tuple:
        """LAUNCH stage for one (index, service) group: the SPMD mesh's
        program invocations (multi-shard), or — mesh-less nodes — the
        fastpath's grouped kernel launches. Returns unfetched handles;
        no device sync happens here (oslint OSL504)."""
        node = self.node
        mesh_handle = None
        kernel_handle = None
        if node.mesh_service is not None:
            mesh_handle = node.mesh_service.launch_msearch(name, svc,
                                                           bodies)
        elif self.config.kernel_batching and len(bodies) >= 2:
            from ..search.executor import launch_msearch_batched
            kernel_handle = launch_msearch_batched(svc.searchers, bodies,
                                                   index_name=name)
        handle = mesh_handle if mesh_handle is not None else kernel_handle
        if handle is not None:
            # batch workspace tenant: the pinned per-request top-k output
            # buffers (score f32 + doc i32 per window slot) the device
            # owes while this batch sits in the in-flight window;
            # released at the handle's deferred sync (or the handle's GC
            # — a wedged/abandoned batch must not pin the stamp).
            # ADVISORY (uncharged): the programs are already launched,
            # so a breaker trip here could only waste the device work by
            # degrading the whole batch to the host loop
            from ..obs.hbm_ledger import LEDGER
            slots = sum(int(b.get("from", 0)) + int(b.get("size", 10))
                        for b in bodies if isinstance(b, dict))
            handle.ws_alloc = LEDGER.register(
                "batch_workspace", slots * 8, owner=handle, charge=False,
                label=f"sched-batch[{name}]x{len(bodies)}")
        return (mesh_handle, kernel_handle)

    def _finish_group(self, name: str, svc, bodies: List[dict],
                      handles: tuple) -> list:
        """FETCH/RENDER stage for one group: sync the mesh launch, then
        coalesce the mesh-declined remainder through the fastpath's
        grouped kernel launches (their eligibility is only known once the
        mesh results are back, so that stage launches-and-fetches here).
        Entries still None take the host loop on their own request
        threads — which also parallelizes the host-side fallback work
        instead of serializing it here."""
        mesh_handle, kernel_handle = handles
        resps: List[Optional[dict]] = [None] * len(bodies)
        if mesh_handle is not None:
            mesh = mesh_handle.fetch()
            if mesh is not None:
                resps = list(mesh)
        todo = [i for i, r in enumerate(resps) if r is None]
        if kernel_handle is not None:
            batched = kernel_handle.fetch()
            if batched is not None:
                for i, r in zip(todo, batched):
                    if resps[i] is None:
                        resps[i] = r
        elif mesh_handle is not None and self.config.kernel_batching \
                and len(todo) >= 2:
            # kernel batching only when there is something to coalesce: a
            # LONE mesh-declined body must take exactly the scheduler-off
            # path (host loop, incl. its shard-view/pruned attribution)
            # — coalescing may change execution only when it fuses
            from ..search.executor import msearch_batched
            batched = msearch_batched(svc.searchers,
                                      [bodies[i] for i in todo],
                                      index_name=name)
            if batched is not None:
                for i, r in zip(todo, batched):
                    if resps[i] is None:
                        resps[i] = r
        for h in (mesh_handle, kernel_handle):
            ms = h.launch_to_fetch_ms() if h is not None else None
            if ms is not None:
                # scheduler-owned handles only: this is the pipeline's
                # deferred-sync window, not a general fetch timer
                METRICS.histogram("serving.launch_to_fetch").record(ms)
                self._local.histogram("serving.launch_to_fetch").record(ms)
        return resps

    def _launch_stage(self, batch: List[_Pending]) -> _InFlight:
        """Dispatcher side of pipelined dispatch: launch every group's
        programs and return the in-flight record. A group whose launch
        raises is recorded as errored — its entries degrade to the host
        loop at completion (never here: the dispatcher must get back to
        `_wait_flush` immediately)."""
        groups = []
        for (name, svc, entries, bodies) in self._group(batch):
            try:
                handles = self._launch_group(name, svc, bodies)
                err = False
            except Exception:                       # noqa: BLE001
                handles = None
                err = True
            for e in entries:
                if e.state == _CLAIMED:   # not cancelled/abandoned since
                    e._stage("launched")
            self._record_launch(entries, handles, err)
            groups.append((name, svc, entries, bodies, handles, err))
        return _InFlight(groups)

    def _enqueue_inflight(self, item: _InFlight) -> None:
        with self._cond:
            self._inflight.append(item)
            self.launched_batches += 1
            depth = len(self._inflight)
            self._inflight_peak = max(self._inflight_peak, depth)
            METRICS.counter("serving.pipeline.launched").inc()
            METRICS.gauge("serving.inflight_depth").set(depth)
            if not self._completion_alive():
                self._start_completion()
            self._cond.notify_all()

    def _completion_alive(self) -> bool:
        return self._cthread is not None and self._cthread.is_alive()

    def _start_completion(self) -> None:
        self._cthread = threading.Thread(
            target=self._completion_loop,
            name="ostpu-serving-completion", daemon=True)
        self._cthread.start()

    def _completion_loop(self) -> None:
        """Completion worker: retire in-flight batches FIFO — device
        sync, oracle re-check, response rendering, future resolution.
        The head batch stays in the window while it is being fetched, so
        the dispatcher's backpressure bound counts it."""
        me = threading.current_thread()
        while True:
            with self._cond:
                while not self._inflight and not self._closed:
                    if not self._cond.wait(self.config.idle_timeout_s) \
                            and not self._inflight:
                        if self._cthread is me:
                            self._cthread = None
                        return
                if not self._inflight:
                    return          # closed and drained
                item = self._inflight[0]
            try:
                with self._meter.stage("fetch"):
                    self._complete(item)
            finally:
                # never strand entries, whatever killed the completion
                for e in item.unresolved():
                    with self._cond:
                        if e.state != _CLAIMED:
                            continue
                        e.state = _DONE
                    e.resp = None
                    e.done.set()
                with self._cond:
                    if self._inflight and self._inflight[0] is item:
                        self._inflight.popleft()
                    self.completed_batches += 1
                    METRICS.counter("serving.pipeline.completed").inc()
                    METRICS.gauge("serving.inflight_depth").set(
                        len(self._inflight))
                    self._cond.notify_all()     # wake the dispatcher

    def _complete(self, item: _InFlight) -> None:
        """Fetch + render + resolve one in-flight batch. Never raises for
        per-group failures: an errored group degrades its entries to the
        host loop (resp None), exactly like the synchronous dispatcher."""
        from ..cluster import faults as _faults
        if _faults.enabled():
            # chaos site: slow-fetch / completion-stage fault injection
            # (cluster/faults.py; the degradation ladder above this —
            # completion wedge -> request-thread direct — is what the
            # injected stall exercises)
            _faults.on_sched_complete(self.node.node_name)
        for (name, svc, entries, bodies, handles, err) in item.groups:
            if err:
                resps = [None] * len(entries)
                with self._cond:
                    self.batch_errors += 1
                METRICS.counter("serving.batch_errors").inc()
            else:
                for e in entries:
                    if e.state == _CLAIMED:
                        e._stage("fetching")
                t_fetch = time.monotonic()
                try:
                    resps = self._finish_group(name, svc, bodies, handles)
                except Exception:                   # noqa: BLE001
                    with self._cond:
                        self.batch_errors += 1
                    METRICS.counter("serving.batch_errors").inc()
                    resps = [None] * len(entries)
                if _fr.RECORDER.enabled:
                    fetch_ms = round(
                        (time.monotonic() - t_fetch) * 1000.0, 3)
                    for e in entries:
                        if e.tl:
                            _fr.RECORDER.record(e.tl, "sched.fetch",
                                                fetch_ms=fetch_ms)
            for e in entries:
                if e.state == _CLAIMED:
                    e._stage("rendering")
            if self.config.oracle:
                # pipelined batches re-run against the direct path too:
                # pipeline on/off must be byte-identical
                self._oracle_check(name, svc, entries, resps)
            self._resolve_entries(entries, resps)

    # ---------------- degraded / oracle paths ----------------

    def _direct(self, name: str, svc, body: dict):
        """Direct per-request execution — exactly what Node.search does
        with the scheduler off (mesh attempt; host loop stays with the
        caller, which treats None as a decline)."""
        if self.node.mesh_service is not None:
            return self.node.mesh_service.try_search(name, svc, body)
        return None

    def _oracle_reference(self, name: str, svc, body: dict):
        """The direct-execution equivalent of a SERVED batched body:
        the mesh when it serves the shape, else a batch-of-one kernel
        launch (probing the grouped kernel path's batch-size
        invariance) — mirroring the launch+fetch stages _dispatch
        composes."""
        if self.node.mesh_service is not None:
            direct = self.node.mesh_service.try_search(name, svc, body)
            if direct is not None:
                return direct
        from ..search.executor import msearch_batched
        single = msearch_batched(svc.searchers, [body], index_name=name)
        return single[0] if single is not None else None

    @staticmethod
    def _normalize(resp) -> Optional[str]:
        if resp is None:
            return None
        out = {k: v for k, v in resp.items() if k != "took"}
        return _json.dumps(out, sort_keys=True, default=repr)

    def _oracle_check(self, name: str, svc, entries: List[_Pending],
                      resps: list) -> None:
        """Run every body through the direct path too and compare (modulo
        wall-clock `took`). Dispatch counters run twice in this mode — it
        exists to prove the identical-results contract, not to serve."""
        for e, r in zip(entries, resps):
            if r is None:
                # declined (or error-degraded): the caller's host loop
                # serves it — nothing BATCHED was produced to verify
                continue
            oracle_body = _copy.deepcopy(e.body)
            oracle_body.pop("_mesh_declined", None)
            try:
                direct = self._oracle_reference(name, svc, oracle_body)
                match = self._normalize(r) == self._normalize(direct)
            except Exception:                       # noqa: BLE001
                match = False
            with self._cond:
                self.oracle_checks += 1
                if not match:
                    self.oracle_mismatches += 1
                    self.last_oracle_mismatch = (
                        f"index [{name}] body "
                        f"{_json.dumps(e.body, default=repr)[:400]}: "
                        f"batched != direct")
            METRICS.counter("serving.oracle_checks").inc()
            if not match:
                METRICS.counter("serving.oracle_mismatches").inc()
                # a coalesced result diverging from direct execution is
                # the worst anomaly this subsystem can produce — freeze
                # the request's full journal for the postmortem
                if _fr.RECORDER.enabled and e.tl:
                    _fr.RECORDER.record(e.tl, "sched.oracle_mismatch",
                                        index=name)
                    _fr.RECORDER.trigger("oracle_mismatch", [e.tl],
                                         note=f"index [{name}]: "
                                              f"batched != direct")

    # ---------------- lifecycle + stats ----------------

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until the pending queue is empty WITHOUT closing the
        scheduler (a transport shutting down must not end the Node-wide
        scheduler's life — another transport, or the dict API, keeps
        coalescing). Returns False when the timeout expired first."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending > 0 or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def close(self, drain: bool = True) -> None:
        """Stop the dispatcher and the completion worker. With drain=True
        pending entries are flushed one last time and in-flight launches
        retired; without it they degrade to direct execution via the
        request-thread timeout path."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
            ct = self._cthread
        if drain:
            if t is not None:
                t.join(timeout=5.0)
            if ct is not None:
                ct.join(timeout=5.0)

    def stats(self) -> dict:
        with self._cond:
            depth = self._pending
            out = {
                "enabled": self.enabled,
                "queue_depth": depth,
                "queue_cap": self.config.queue_cap,
                "effective_queue_cap": self._effective_cap(),
                "max_batch": self.config.max_batch,
                "max_wait_us": self.config.max_wait_us,
                "submitted": self.submitted,
                "batched_served": self.batched_served,
                "declined": self.declined,
                "bypassed": self.bypassed,
                "rejected": self.rejected,
                "cancelled_dropped": self.cancelled_dropped,
                "direct_fallbacks": self.direct_fallbacks,
                "batch_errors": self.batch_errors,
                "flushes": self.flushes,
                "flush_reasons": dict(self.flush_reasons),
                "lanes": {lane: {"flushed": self.lane_flushed[lane]}
                          for lane in LANES},
                "oracle": {"enabled": self.config.oracle,
                           "checks": self.oracle_checks,
                           "mismatches": self.oracle_mismatches},
                "pipeline": {
                    "depth": self.config.pipeline_depth,
                    "inflight": len(self._inflight),
                    "inflight_peak": self._inflight_peak,
                    "launched_batches": self.launched_batches,
                    "completed_batches": self.completed_batches,
                    "cancelled_inflight": self.cancelled_inflight,
                    "completion_abandoned": self.completion_abandoned,
                },
            }
        out["pipeline"].update(self._meter.snapshot())
        out["batch_size"] = self._local.percentiles("serving.batch_size")
        out["queue_wait_ms"] = self._local.percentiles("serving.queue_wait")
        out["launch_to_fetch_ms"] = self._local.percentiles(
            "serving.launch_to_fetch")
        return out

    def note_bypass(self) -> None:
        with self._cond:
            self.bypassed += 1
        METRICS.counter("serving.bypassed").inc()
