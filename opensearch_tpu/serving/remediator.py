"""Closed-loop remediation actuator: from SLO burn to bounded action.

Every prior observability layer REPORTS: the burn-rate engine
(obs/slo.py) says a lane's objective is burning, query insights
(obs/insights.py) says WHICH query shapes are responsible, the member
failure detector (cluster/failure.py) says which peer is sick. This
module is the first subsystem that ACTS on those findings — the
load-shed actuator ROADMAP item 1 has promised since round 5. It
subscribes to firing ``slo.burn`` alerts and takes bounded, reversible
actions at the admission boundary:

- **shed_shape** — the alert's ``top_fingerprints`` become a shed set.
  At admission (rest/client.py, cluster/distnode.py) the request body is
  re-fingerprinted with `insights.fingerprint(body, lane)`; a matching
  BATCH-lane request is rejected with 429 + a ``Retry-After`` header
  (the shed), a matching INTERACTIVE request is demoted to the batch
  lane (the deprioritization — SCHEDULING priority only: callers keep
  recording SLIs/insights under the origin lane, or the demotion would
  hide the burn from the SLO that fired it) — offending shapes lose
  priority, they are never silently dropped mid-flight, and unlisted
  shapes are never touched. Fingerprint derivation is deterministic,
  so the decision for a given body is byte-stable across threads and
  nodes.
- **tighten_admission** — while engaged, the serving scheduler's
  admission cap contracts (`queue_cap * admission_factor`, 429s fire
  earlier with honest Retry-After hints derived from queue depth) and
  every wlm token-bucket admission spends ``wlm_cost`` tokens instead
  of one (utils/wlm.py) — the front door narrows without any
  configuration mutation to undo later.
- **deprioritize_member** — for transport-shaped alerts, the worst
  suspect in the `MemberFailureDetector` is PINNED to the back of every
  shard's copy preference (`member_fd.pin`); unlike ordinary suspicion,
  a lucky probe does not un-demote it — only this actuator's release
  path (`member_fd.unpin`) does.

Every action is **bounded and self-releasing** (oslint OSL603 enforces
the pairing statically): a hard TTL (`ttl_s`) releases it even if the
evaluation loop dies, and the green path releases it once the alerting
SLO has read ``ok`` continuously for `green_hold_s`. Hysteresis: the
multi-window burn rate already gates engagement on sustained pressure,
re-alerts within `engage_cooldown_s` refresh the existing actions'
TTLs instead of stacking new ones, and at most `max_actions` are ever
live. While a load-shaped SLO KEEPS firing with remediation engaged,
the tick loop periodically **re-attributes** — alerts are
edge-triggered and attribution is completion-time accounting, so a
flooding shape whose requests were still in flight at the first edge
only shows up in the window later; the actuator keeps pulling the
live top-K (paced by the same cooldown, same bounds) until the burn
clears. Every transition lands a flight-recorder event
(``remediation.engage`` / ``remediation.release``), an engage freezes a
``remediation`` dump bundle, and `GET /_remediation` serves the live
action table — federated across the fleet on the `/_internal` plane
like the observatory surfaces.

Disarmed (the default) the actuator is inert: the admission hot path is
one attribute read (`self._active`), and fingerprints are only derived
while a shed set is live. Tests and the traffic harness inject private
instances (`node.remediation`, `DistClusterNode.remediation_engine`) —
the obs_registry pattern.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from ..utils.metrics import METRICS, MetricsRegistry
from ..utils.wlm import PressureRejectedException

__all__ = ["RemediationConfig", "Action", "Remediator", "REMEDIATOR"]

KINDS = ("shed_shape", "tighten_admission", "deprioritize_member")

# alert kinds whose blame is load-shaped (shed/tighten applies) vs
# transport-shaped (member deprioritization applies). rejection_rate is
# deliberately in NEITHER set: tightening admission on a rejection burn
# would manufacture more rejections and self-sustain the alert.
_LOAD_KINDS = ("latency", "error_rate", "availability")
_TRANSPORT_KINDS = ("counter_ratio", "availability")

# Retry-After hints are clamped: an honest "come back later" must never
# tell a client to go away for a whole TTL epoch
_RETRY_AFTER_CAP_S = 30.0


class RemediationConfig:
    """Bounds and clocks for every action the actuator may take (the
    action table in docs/RESILIENCE.md "Self-healing loop")."""

    def __init__(self, ttl_s: Optional[float] = None,
                 green_hold_s: Optional[float] = None,
                 engage_cooldown_s: Optional[float] = None,
                 max_actions: int = 8,
                 max_shed_shapes: int = 3,
                 admission_factor: Optional[float] = None,
                 wlm_cost: float = 2.0,
                 retry_after_s: float = 1.0):
        env = os.environ
        # hard auto-release bound: an engaged action with a dead
        # evaluation loop still expires (checked lazily at admission too)
        self.ttl_s = float(
            ttl_s if ttl_s is not None
            else env.get("OPENSEARCH_TPU_REMEDIATION_TTL_S", 60.0))
        # release hysteresis: the alerting SLO must read ok continuously
        # this long before the action lifts (a single green tick between
        # two burn windows must not flap the actuator)
        self.green_hold_s = float(
            green_hold_s if green_hold_s is not None
            else env.get("OPENSEARCH_TPU_REMEDIATION_HOLD_S", 2.0))
        # engage hysteresis: re-alerts inside the cooldown refresh TTLs
        # instead of stacking new actions
        self.engage_cooldown_s = float(
            engage_cooldown_s if engage_cooldown_s is not None
            else env.get("OPENSEARCH_TPU_REMEDIATION_COOLDOWN_S", 1.0))
        self.max_actions = int(max_actions)
        self.max_shed_shapes = int(max_shed_shapes)
        # scheduler queue-cap contraction while tighten_admission holds
        self.admission_factor = float(
            admission_factor if admission_factor is not None
            else env.get("OPENSEARCH_TPU_REMEDIATION_ADMISSION", 0.5))
        # wlm token cost per admission while tighten_admission holds
        self.wlm_cost = float(wlm_cost)
        self.retry_after_s = float(retry_after_s)
        if not 0.0 < self.admission_factor <= 1.0:
            raise ValueError("admission_factor must be in (0, 1]")
        if self.ttl_s <= 0:
            raise ValueError("remediation ttl_s must be positive")

    def describe(self) -> dict:
        return {"ttl_s": self.ttl_s, "green_hold_s": self.green_hold_s,
                "engage_cooldown_s": self.engage_cooldown_s,
                "max_actions": self.max_actions,
                "max_shed_shapes": self.max_shed_shapes,
                "admission_factor": self.admission_factor,
                "wlm_cost": self.wlm_cost}


class Action:
    """One live remediation action: what was engaged, why, and when it
    must be gone again."""

    __slots__ = ("kind", "target", "slo", "engaged_mono", "ttl_s",
                 "green_since_mono", "meta")

    def __init__(self, kind: str, target: str, slo: str, now: float,
                 ttl_s: float, meta: Optional[dict] = None):
        self.kind = kind
        self.target = target
        self.slo = slo
        self.engaged_mono = now
        self.ttl_s = float(ttl_s)
        self.green_since_mono: Optional[float] = None
        self.meta = dict(meta or {})

    @property
    def key(self) -> tuple:
        return (self.kind, self.target)

    def expired(self, now: float) -> bool:
        return now - self.engaged_mono >= self.ttl_s

    def describe(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        return {"kind": self.kind, "target": self.target,
                "slo": self.slo,
                "age_s": round(now - self.engaged_mono, 3),
                "ttl_s": self.ttl_s,
                "ttl_remaining_s": round(
                    max(self.ttl_s - (now - self.engaged_mono), 0.0), 3),
                **({"meta": self.meta} if self.meta else {})}


class Remediator:
    """The closed control loop. `arm()` subscribes it to an SLO engine's
    firing alerts and a sampler's tick (the release clock); `admit()` is
    the only call on the serving hot path."""

    def __init__(self, config: Optional[RemediationConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None):
        self.config = config or RemediationConfig()
        self.registry = registry if registry is not None else METRICS
        self._recorder = recorder      # None -> module RECORDER, lazily
        self._lock = threading.Lock()
        self._actions: "OrderedDict[tuple, Action]" = OrderedDict()
        self._history: deque = deque(maxlen=64)
        # wiring (set by arm)
        self.armed = False
        self.engine = None             # obs.slo.SLOEngine
        self.sampler = None
        self.member_fd = None          # cluster.failure.MemberFailureDetector
        self.insights_engine = None    # None -> module INSIGHTS, lazily
        self._last_engage_mono: Dict[str, float] = {}   # per-SLO cooldown
        # load-shaped SLOs with live remediation: while one KEEPS
        # firing, tick() re-pulls attribution and widens the shed set
        # (bounded by max_shed_shapes per pull / max_actions total) —
        # alerts are edge-triggered, but a flooding shape whose
        # requests were still in flight at the first edge only becomes
        # visible to completion-time accounting later
        self._burning_ctx: Dict[str, dict] = {}
        # counters (mutated under the lock, mirrored into the registry)
        self.engaged_total = 0
        self.released_total = 0
        self.shed_total = 0
        self.deprioritized_total = 0
        # ---- admission fast-path snapshots (GIL-atomic attribute swaps;
        # the hot path reads these WITHOUT the lock — the sanctioned
        # lock-free pattern, see docs/STATIC_ANALYSIS.md "Concurrency
        # suite": single-reference rebind-then-swap only; any
        # read-modify-write here must move under self._lock) ----
        self._active = False
        self._shed: frozenset = frozenset()
        self._tightened = False
        # earliest TTL deadline among live actions: admit() consults it
        # so the hard bound holds even with a dead evaluation loop
        self._next_expiry = float("inf")

    # ---------------- arm / disarm ----------------

    def arm(self, node=None, slo_engine=None, sampler=None,
            member_fd=None, insights=None) -> None:
        """Wire the loop: alerts in from the SLO engine, the release
        clock from the sampler tick. Idempotent."""
        if insights is not None:
            self.insights_engine = insights
        if slo_engine is None and node is not None:
            slo_engine = getattr(node, "slo", None)
        if slo_engine is None:
            from ..obs.slo import SLO_ENGINE
            slo_engine = SLO_ENGINE
        new_sampler = sampler if sampler is not None \
            else slo_engine.sampler
        # re-arming against a DIFFERENT engine/sampler must drop the
        # old subscriptions first, or the abandoned engine's alerts
        # would keep driving this actuator (idempotence means one live
        # wiring, not an accumulating set)
        if self.engine is not None and self.engine is not slo_engine:
            self.engine.remove_alert_listener(self.on_alert)
        if self.sampler is not None and self.sampler is not new_sampler:
            self.sampler.remove_listener(self._on_tick)
        self.engine = slo_engine
        self.sampler = new_sampler
        if member_fd is not None:
            self.member_fd = member_fd
        self.engine.add_alert_listener(self.on_alert)
        self.sampler.add_listener(self._on_tick)
        self.armed = True

    def disarm(self) -> None:
        """Release every live action and unsubscribe. The actuator must
        never leave state behind: disarm returns the node to exactly the
        unremediated configuration."""
        # flip armed FIRST: an in-flight tick()'s re-attribution pass
        # (which snapshots _burning_ctx before we clear it) checks the
        # flag per engagement and must not re-engage after the release
        self.armed = False
        if self.engine is not None:
            self.engine.remove_alert_listener(self.on_alert)
        if self.sampler is not None:
            self.sampler.remove_listener(self._on_tick)
        released = []
        with self._lock:
            for action in list(self._actions.values()):
                released.append(
                    self._release_locked(action, why="disarm"))
            self._burning_ctx.clear()
            self._rebuild_locked()
        for row in released:
            self._record_release(row)
        self.armed = False

    # ---------------- the engage side (alert listener) ----------------

    def on_alert(self, alert: dict) -> None:
        """One firing `slo.burn` alert -> the engage policy:

        - load-shaped kinds (latency / error_rate / availability): shed
          the alert's top fingerprints + tighten admission;
        - transport-shaped kinds (counter_ratio / availability): pin the
          failure detector's worst suspect member;
        - rejection_rate: no amplification — rejections are already the
          actuator's own exhaust, acting on them would self-sustain.

        Re-alerts inside `engage_cooldown_s` refresh live TTLs only."""
        if not isinstance(alert, dict):
            return
        slo = str(alert.get("slo", ""))
        kind = str(alert.get("slo_kind", ""))
        now = time.monotonic()
        with self._lock:
            last = self._last_engage_mono.get(slo)
            refresh_only = (last is not None
                            and now - last < self.config.engage_cooldown_s)
            self._last_engage_mono[slo] = now
            if refresh_only:
                for a in self._actions.values():
                    if a.slo == slo:
                        a.engaged_mono = now
                        a.green_since_mono = None
                # the lazy-expiry snapshot must follow the refreshed
                # TTLs, or admit() would run a full tick per request
                # once the ORIGINAL deadline passes
                self._rebuild_locked()
                return
        if kind in _LOAD_KINDS:
            fps = [e.get("fingerprint")
                   for e in (alert.get("top_fingerprints") or [])
                   if isinstance(e, dict) and e.get("fingerprint")]
            for key in fps[: self.config.max_shed_shapes]:
                self._engage("shed_shape", str(key), slo,
                             meta={"lane": alert.get("lane")})
            self._engage("tighten_admission", "", slo)
        if kind in _TRANSPORT_KINDS and self.member_fd is not None:
            member = self._worst_suspect()
            if member is not None:
                self._engage("deprioritize_member", member, slo)
        if kind in _LOAD_KINDS or kind in _TRANSPORT_KINDS:
            with self._lock:
                self._burning_ctx[slo] = {"kind": kind,
                                          "lane": alert.get("lane")}

    def _worst_suspect(self) -> Optional[str]:
        """The member the failure detector blames most (max consecutive
        failures, name-ordered tie break); None when nobody is suspect —
        a transport burn with no named culprit engages nothing."""
        try:
            st = self.member_fd.stats()
        except Exception:       # noqa: BLE001 — blame input is advisory
            return None
        suspect = dict(st.get("suspect") or {})
        for m in st.get("deprioritized") or []:
            suspect.setdefault(m, 1 << 30)
        if not suspect:
            return None
        return sorted(suspect.items(), key=lambda kv: (-kv[1], kv[0]))[0][0]

    def _engage(self, kind: str, target: str, slo: str,
                meta: Optional[dict] = None,
                guard_armed: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if guard_armed and not self.armed:
                # listener-driven engage racing a disarm: the armed
                # re-check must be ATOMIC with the insert, or a tick in
                # flight could strand an action (and a member pin) with
                # every release listener already gone
                return
            existing = self._actions.get((kind, target))
            if existing is not None:
                # refresh: hysteresis extends the bound, never stacks
                # (and the lazy-expiry snapshot follows the new TTL)
                existing.engaged_mono = now
                existing.green_since_mono = None
                self._rebuild_locked()
                return
            if len(self._actions) >= self.config.max_actions:
                self.registry.counter("remediation.bounded_out").inc()
                return
            action = Action(kind, target, slo, now, self.config.ttl_s,
                            meta)
            self._actions[action.key] = action
            self.engaged_total += 1
            self._history.append({"event": "engage", "kind": kind,
                                  "target": target, "slo": slo,
                                  "at_mono": round(now, 6)})
            self._rebuild_locked()
        if kind == "deprioritize_member" and self.member_fd is not None:
            self.member_fd.pin(target)
        self.registry.counter("remediation.engaged_total").inc()
        rec = self._rec()
        if rec is not None and rec.enabled:
            tl = rec.start("remediation", action=kind, slo=slo)
            if tl:
                rec.record(tl, "remediation.engage", action=kind,
                           target=target, slo=slo,
                           ttl_s=self.config.ttl_s)
                rec.trigger("remediation", [tl],
                            note=f"remediation [{kind}] target "
                                 f"[{target or '-'}] for SLO [{slo}]")

    # ---------------- the release side (sampler tick) ----------------

    def _on_tick(self, _sampler) -> None:
        self.tick()

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One release pass: TTL expiry (hard bound) and green release
        (the alerting SLO read ok for `green_hold_s`). Returns the
        release records, for tests and the harness gate."""
        now = time.monotonic() if now is None else now
        released: List[dict] = []
        with self._lock:
            for action in list(self._actions.values()):
                if action.expired(now):
                    released.append(
                        self._release_locked(action, why="ttl", now=now))
                    continue
                if self._slo_green(action.slo):
                    if action.green_since_mono is None:
                        action.green_since_mono = now
                    elif (now - action.green_since_mono
                          >= self.config.green_hold_s):
                        released.append(self._release_locked(
                            action, why="green", now=now))
                else:
                    action.green_since_mono = None
            if released:
                self._rebuild_locked()
        for rec_row in released:
            self._record_release(rec_row)
        self._reattribute(now)
        return released

    def _reattribute(self, now: float) -> None:
        """While an SLO KEEPS firing with remediation engaged,
        periodically re-pull attribution and keep the actions live.
        Alerts are edge-triggered: the first edge's top-K can miss the
        true offender when its requests were still in flight
        (completion-time accounting), and a burn outlasting `ttl_s`
        would otherwise silently lapse its tighten/pin actions with no
        new edge to re-engage them. Paced by `engage_cooldown_s`,
        bounded like any engagement."""
        with self._lock:
            ctxs = dict(self._burning_ctx)
        for slo, ctx in ctxs.items():
            if not self.armed:
                # disarm raced this pass: re-engaging now would strand
                # actions with every release listener already removed
                return
            if self._slo_green(slo):
                with self._lock:
                    self._burning_ctx.pop(slo, None)
                continue
            with self._lock:
                last = self._last_engage_mono.get(slo, -1e18)
                if now - last < self.config.engage_cooldown_s:
                    continue
                self._last_engage_mono[slo] = now
            kind = ctx.get("kind")
            if kind in _TRANSPORT_KINDS and self.member_fd is not None:
                member = self._worst_suspect()
                if member is not None:
                    self._engage("deprioritize_member", member, slo,
                                 meta={"via": "reattribution"},
                                 guard_armed=True)
            if kind not in _LOAD_KINDS:
                continue
            # still-burning load alert: keep the admission tightened
            # (refresh, or re-engage if it TTL'd out mid-burn) and
            # widen the shed set from the live window
            self._engage("tighten_admission", "", slo,
                         guard_armed=True)
            window_s = self._slo_window(slo)
            try:
                fps = self._insights().top_fingerprints(
                    window_s, n=self.config.max_shed_shapes)
            except Exception:   # noqa: BLE001 — attribution is advisory
                continue
            for e in fps:
                key = (e or {}).get("fingerprint")
                if key:
                    self._engage("shed_shape", str(key), slo,
                                 meta={"lane": ctx.get("lane"),
                                       "via": "reattribution"},
                                 guard_armed=True)

    def _slo_window(self, slo_name: str) -> float:
        eng = self.engine
        try:
            s = eng._slos.get(slo_name) if eng is not None else None
        except Exception:       # noqa: BLE001
            s = None
        return float(getattr(s, "slow_window_s", 60.0))

    def _insights(self):
        if self.insights_engine is not None:
            return self.insights_engine
        from ..obs.insights import INSIGHTS
        return INSIGHTS

    def _slo_green(self, slo_name: str) -> bool:
        """ok iff the engine knows the objective and it is not firing;
        a disarmed/unknown objective reads green (nothing left to hold
        the action open — the TTL still bounds it)."""
        eng = self.engine
        if eng is None:
            return True
        try:
            st = eng._status.get(slo_name)       # engine-lock-free read
        except Exception:       # noqa: BLE001 — release must never wedge
            return True
        return st is None or st.get("state") != "firing"

    def _release_locked(self, action: Action, why: str,
                        now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        self._actions.pop(action.key, None)
        self.released_total += 1
        row = {"event": "release", "kind": action.kind,
               "target": action.target, "slo": action.slo, "why": why,
               "held_s": round(now - action.engaged_mono, 3),
               "at_mono": round(now, 6)}
        self._history.append(row)
        return row

    def _record_release(self, row: dict) -> None:
        if row["kind"] == "deprioritize_member" \
                and self.member_fd is not None:
            # liveness check AND unpin atomically under the actuator
            # lock: a concurrent re-engage inserts its action under the
            # same lock before pinning, so either we see it live (skip
            # the unpin) or our unpin completes before its pin lands —
            # a stale release can never strip a live action's pin.
            # (lock order self._lock -> fd._lock; the detector never
            # calls back into the actuator, so no inversion exists)
            with self._lock:
                if ("deprioritize_member",
                        row["target"]) not in self._actions:
                    self.member_fd.unpin(row["target"])
        self.registry.counter("remediation.released_total").inc()
        rec = self._rec()
        if rec is not None and rec.enabled:
            tl = rec.start("remediation", action=row["kind"],
                           slo=row["slo"])
            if tl:
                rec.record(tl, "remediation.release",
                           action=row["kind"], target=row["target"],
                           why=row["why"], held_s=row["held_s"])

    def _rebuild_locked(self) -> None:
        """Recompute the lock-free admission snapshots. Called under the
        lock; the swaps themselves are single attribute writes."""
        shed = frozenset(a.target for a in self._actions.values()
                         if a.kind == "shed_shape")
        tightened = any(a.kind == "tighten_admission"
                        for a in self._actions.values())
        self._shed = shed
        self._tightened = tightened
        self._active = bool(self._actions)
        self._next_expiry = min(
            (a.engaged_mono + a.ttl_s for a in self._actions.values()),
            default=float("inf"))
        self.registry.gauge("remediation.active_actions").set(
            float(len(self._actions)))

    # ---------------- the admission surface (hot path) ----------------

    @property
    def active(self) -> bool:
        return self._active

    @property
    def tightened(self) -> bool:
        return self._tightened

    def queue_factor(self) -> float:
        """Scheduler admission contraction: 1.0 unremediated."""
        return self.config.admission_factor if self._tightened else 1.0

    def wlm_cost(self) -> float:
        """wlm token cost per admission: 1.0 unremediated."""
        return self.config.wlm_cost if self._tightened else 1.0

    def admit(self, body, lane: str) -> str:
        """The admission-time fingerprint match. Returns the (possibly
        demoted) lane; raises PressureRejectedException (429 +
        Retry-After) for a shed batch-lane shape. Deterministic per
        body+lane — identical bodies always get identical decisions —
        and O(1) when no shed set is live."""
        if not self._active:
            return lane
        # the TTL is a HARD bound even with a dead evaluation loop:
        # admission itself retires expired actions lazily (the
        # RemediationConfig contract) — one monotonic read on the
        # already-remediated path, nothing on the inactive one
        if time.monotonic() >= self._next_expiry:
            self.tick()
            if not self._active:
                return lane
        shed = self._shed
        if not shed:
            return lane
        from ..obs.insights import fingerprint
        key = fingerprint(body if isinstance(body, dict) else {},
                          lane)[0]
        if key not in shed:
            return lane
        if lane == "batch":
            with self._lock:
                self.shed_total += 1
                retry = self._retry_after_locked(key)
            self.registry.counter("remediation.shed_total").inc()
            # the consistent rejection naming (docs/SERVING.md): every
            # admission-layer 429 — wlm, scheduler, remediation —
            # mirrors into serving.lane.{lane}.rejected
            self.registry.counter(
                f"serving.lane.{lane}.rejected").inc()
            raise PressureRejectedException(
                f"shape [{key}] is being shed by remediation "
                f"(SLO burn); retry after {retry:.0f}s",
                retry_after_s=retry, source="remediation")
        # interactive traffic is never hard-rejected by shape: it is
        # DEPRIORITIZED — demoted to the batch lane, where it only takes
        # the scheduler's leftover flush slots
        with self._lock:
            self.deprioritized_total += 1
        self.registry.counter("remediation.deprioritized_total").inc()
        return "batch"

    def _retry_after_locked(self, key: str) -> float:
        a = self._actions.get(("shed_shape", key))
        if a is None:
            return self.config.retry_after_s
        remaining = a.ttl_s - (time.monotonic() - a.engaged_mono)
        return min(max(remaining, self.config.retry_after_s, 1.0),
                   _RETRY_AFTER_CAP_S)

    # ---------------- surfaces ----------------

    def status(self) -> dict:
        """`GET /_remediation` payload: live action table, recent
        engage/release history, bounds, counters."""
        now = time.monotonic()
        with self._lock:
            active = [a.describe(now) for a in self._actions.values()]
            history = list(self._history)
            counters = {"engaged_total": self.engaged_total,
                        "released_total": self.released_total,
                        "shed_total": self.shed_total,
                        "deprioritized_total": self.deprioritized_total}
        return {"armed": self.armed, "active": active,
                "tightened": self._tightened,
                "shed_fingerprints": sorted(self._shed),
                "history": history, "counters": counters,
                "config": self.config.describe()}

    def stats(self) -> dict:
        """`_nodes/stats` "remediation" block (compact: no history)."""
        with self._lock:
            return {"armed": self.armed,
                    "active_actions": len(self._actions),
                    "tightened": self._tightened,
                    "engaged_total": self.engaged_total,
                    "released_total": self.released_total,
                    "shed_total": self.shed_total,
                    "deprioritized_total": self.deprioritized_total}

    def reset(self) -> None:
        """Test/bench isolation hook (the METRICS.reset pattern):
        disarm + drop history and counters."""
        self.disarm()
        with self._lock:
            self._history.clear()
            self._last_engage_mono.clear()
            self.engaged_total = self.released_total = 0
            self.shed_total = self.deprioritized_total = 0

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from ..obs.flight_recorder import RECORDER
        return RECORDER


# process-default actuator (one node per process, like METRICS/RECORDER);
# disarmed until a Node with OPENSEARCH_TPU_REMEDIATION=1, the traffic
# harness, or an operator arms it
REMEDIATOR = Remediator()
