"""Version info. Analog of reference `server/src/main/java/org/opensearch/Version.java`."""

__version__ = "0.1.0"
LUCENE_ANALOG_VERSION = "tpu-csr-1"  # postings/codec layout version
