"""opensearch_tpu — a TPU-native search & analytics engine.

A from-scratch rebuild of the capabilities of OpenSearch (reference:
/root/reference, Java/Lucene) designed TPU-first:

- Host (Python): REST-style API, cluster state, mappings, analysis, the
  write path (engine + translog), query planning.
- Device (JAX/XLA/Pallas): query execution. Inverted-index segments live in
  HBM as CSR posting blocks; BM25 scoring is a batched gather -> scatter-add
  -> fused top-k instead of Lucene's per-doc scoring loop
  (reference: lucene BulkScorer driven by
  server/src/main/java/org/opensearch/search/query/QueryPhase.java).
- Distribution: shards map onto a `jax.sharding.Mesh` axis; the coordinator
  scatter/gather of reference
  `action/search/TransportSearchAction.java` becomes `shard_map` with a
  per-device top-k followed by an `all_gather` merge over ICI.
"""

import os as _os

if _os.environ.get("OPENSEARCH_TPU_LOCKWITNESS") == "1":
    # arm BEFORE any submodule import constructs a lock: the witness
    # wraps locks at creation, so it must patch the threading factories
    # first (see devtools/lockwitness.py and docs/STATIC_ANALYSIS.md)
    from .devtools import lockwitness as _lockwitness
    _lockwitness.install()

from .version import __version__

__all__ = ["__version__", "Node", "RestClient"]


def __getattr__(name):
    # lazy to keep `import opensearch_tpu` light and cycle-free
    if name == "Node":
        from .cluster.node import Node
        return Node
    if name == "RestClient":
        from .rest.client import RestClient
        return RestClient
    raise AttributeError(name)
