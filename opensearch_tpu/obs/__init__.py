"""Observability forensics: the flight recorder (per-request black-box
event journal with anomaly-triggered dumps) and the hot-threads stack
sampler. docs/OBSERVABILITY.md documents the event schema, the dump
triggers, and the retention/overhead knobs."""

from .flight_recorder import (FlightRecorder, RECORDER, current,
                              reset_current, set_current)
from .hot_threads import hot_threads

__all__ = ["FlightRecorder", "RECORDER", "current", "set_current",
           "reset_current", "hot_threads"]
