"""Observability forensics: the flight recorder (per-request black-box
event journal with anomaly-triggered dumps), the hot-threads stack
sampler, the HBM ledger (attributed device-memory accounting, the sole
breaker-charge path — oslint OSL506), and per-query device cost
accounting (predicted vs. actual bytes gathered). docs/OBSERVABILITY.md
documents the event schema, dump triggers, tenant taxonomy, and the
cost-model formulas."""

from .flight_recorder import (FlightRecorder, RECORDER, current,
                              reset_current, set_current)
from .hbm_ledger import LEDGER, HBMLedger
from .hot_threads import hot_threads

__all__ = ["FlightRecorder", "RECORDER", "current", "set_current",
           "reset_current", "hot_threads", "LEDGER", "HBMLedger"]
