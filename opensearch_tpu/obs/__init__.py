"""Observability forensics: the flight recorder (per-request black-box
event journal with anomaly-triggered dumps), the hot-threads stack
sampler, the HBM ledger (attributed device-memory accounting, the sole
breaker-charge path — oslint OSL506), per-query device cost accounting
(predicted vs. actual bytes gathered), the time-series retention ring
(`timeseries.py` — bounded periodic registry snapshots behind
`_nodes/stats/history`, oslint OSL509), and the SLO burn-rate engine
(`slo.py` — declared objectives over sliding windows, `GET /_slo`).
docs/OBSERVABILITY.md documents the event schema, dump triggers, tenant
taxonomy, cost-model formulas, and the fleet/SLO model."""

from .flight_recorder import (FlightRecorder, RECORDER, current,
                              reset_current, set_current)
from .hbm_ledger import LEDGER, HBMLedger
from .hot_threads import hot_threads
from .slo import SLO, SLO_ENGINE, SLOEngine, default_slos, ingest_slos
from .timeseries import SAMPLER, TimeSeriesSampler

__all__ = ["FlightRecorder", "RECORDER", "current", "set_current",
           "reset_current", "hot_threads", "LEDGER", "HBMLedger",
           "SAMPLER", "TimeSeriesSampler", "SLO", "SLOEngine",
           "SLO_ENGINE", "default_slos", "ingest_slos"]
