"""HBM ledger: the single source of truth for device-memory accounting.

PR 3's telemetry observes the *time* domain and the flight recorder (PR 6)
the *event* domain; this module owns the *byte* domain. Every HBM tenant —
segment column pytrees (`index/segment.py:_build_device_arrays`),
partial-residency term arrays (`Segment.pruned_arrays`), fastpath aligned
postings and their filter-specialized copies, cached filter doc lists,
quality-tier views, nested-sort columns, per-shape compiled programs, and
the serving scheduler's in-flight batch workspaces — registers an
*attributed allocation* (tenant kind × segment × device × label) here, and
the circuit-breaker charge is DERIVED from the registration instead of
each module calling `breaker.add_estimate` ad hoc (oslint OSL506 enforces
that the ledger is the sole charge path).

Why: the north star (≥20× BM25 at fixed recall) is won in the byte domain.
ROADMAP item 1 (impact-quantized postings) claims a smaller HBM footprint
and fewer bytes moved per query; item 5's admission control needs real
HBM pressure signals. Neither is arguable without an attributed baseline —
"how many bytes does tenant X hold, and who moved what per query" must be
answerable before and after those PRs.

Design:

- **Attributed allocations.** `register()` returns an `Allocation` carrying
  (kind, nbytes, segment name/uid, device, label). Live allocations are
  indexed for the rollups `_nodes/stats` ("hbm"), `GET /_cat/segments`
  (per-segment device residency) and `scripts/hbm_report.py` serve.
- **Derived breaker charges.** A charged registration calls
  `breaker.add_estimate` on the breaker installed at charge time and
  remembers it, so the paired release always credits the same breaker
  even if a later `Node` swapped the process default (test isolation).
  The standing invariant — `sum(live charged bytes) == breaker.used` per
  breaker — is checked by `verify_breakers()` after every tier-1 test.
- **Release exactness.** `release()` is idempotent per allocation; an
  `owner` object ties release to a `weakref.finalize`, so a tenant GC'd
  without an explicit release still credits the breaker exactly once.
- **Peak tracking.** Total and per-kind peaks survive releases — the
  `extra.hbm` bench stamp is the committed footprint baseline future PRs
  must beat.
- **Silicon cross-check.** On a real device backend `check_device()`
  compares the ledger total against `device.memory_stats()["bytes_in_use"]`
  and triggers a flight-recorder anomaly dump (`hbm_drift`) past the
  threshold — the ledger audits itself against the hardware.

Flight-recorder linkage: registrations and releases on a request timeline
emit `hbm.build` / `hbm.evict` events, and a breaker trip emits
`hbm.breaker_trip`, so residency churn shows up on the same per-request
journal as scheduler and ladder events.
"""

from __future__ import annotations

import itertools
import os
import threading
import weakref
from typing import Any, Dict, List, Optional

from ..utils.metrics import METRICS
from . import flight_recorder as _fr

__all__ = ["Allocation", "HBMLedger", "LEDGER"]

# tenant taxonomy (docs/OBSERVABILITY.md "memory and cost"): free-form
# strings are accepted, but the known kinds keep dashboards stable
KINDS = (
    "segment_columns",      # Segment.device_arrays full pytree
    "impact_postings",      # codec-v2 quantized impact planes (u8/u16)
    "block_max",            # codec-v2 block-max sidecars (host, advisory)
    "postings_tfs",         # f32 tf planes promoted back onto v2 segments
    "partial_columns",      # Segment.pruned_arrays per-field arrays
    "aligned_postings",     # fastpath AlignedPostings (docs + packed tfdl)
    "filtered_postings",    # filter-specialized aligned copies
    "filter_list",          # cached FilterList device doc lists
    "quality_tier",         # static-pruning view masks/doc lists
    "nested_sort",          # compiler _nested_sort_values columns
    "phrase_pairs",         # resident phrase (doc, pos) pair arrays
    "mesh_postings",        # SPMD stacked per-shard postings/pairs
    "mesh_columns",         # SPMD stacked agg columns/ordinals/masks
    "program",              # compiled-program footprints (advisory)
    "batch_workspace",      # scheduler in-flight batch output buffers
)


class Allocation:
    """One live attributed device-memory tenant."""

    __slots__ = ("aid", "kind", "nbytes", "segment", "seg_uid", "device",
                 "label", "charged", "breaker", "live", "evictor")

    def __init__(self, aid: int, kind: str, nbytes: int, segment: str,
                 seg_uid: Optional[int], device: str, label: str,
                 breaker, evictor=None) -> None:
        self.aid = aid
        self.kind = kind
        self.nbytes = int(nbytes)
        self.segment = segment
        self.seg_uid = seg_uid
        self.device = device
        self.label = label
        self.breaker = breaker        # breaker CHARGED at register time
        self.charged = breaker is not None
        self.live = True
        # weak callable releasing this tenant's residency under memory
        # pressure (Segment.evict_device); None = not evictable
        self.evictor = evictor


def _device_key(device) -> str:
    if device is None:
        return "default"
    return str(device)


class HBMLedger:
    """Thread-safe attributed-allocation table + derived breaker charges.

    One per process (module singleton `LEDGER`), like TRACER / METRICS /
    RECORDER — one node per process is the deployment reality; multi-node
    tests share the table (allocations carry their own breaker refs, so
    per-node budgets stay exact)."""

    def __init__(self) -> None:
        # RLock: a weakref finalizer (-> _release_id) can fire at any
        # allocation point, including inside our own locked sections on
        # the same thread — a plain Lock would self-deadlock there
        self._lock = threading.RLock()
        self._breaker = None
        self._aid = itertools.count(1)
        self._allocs: Dict[int, Allocation] = {}
        self._by_kind: Dict[str, int] = {}
        self._peak_by_kind: Dict[str, int] = {}
        self._total = 0
        self._peak = 0
        # id(breaker) -> (breaker, charged bytes): the invariant ledger
        self._charged: Dict[int, list] = {}
        self.registrations = 0
        self.releases = 0
        self.breaker_trips = 0
        self.drift_checks = 0
        self.drift_dumps = 0
        self._last_drift_dump = 0.0    # monotonic; rate-limits dumps
        # LRU-by-segment-plane eviction under pressure: (seg_uid, device)
        # -> last-touch sequence. Writes are lock-free (GIL-atomic dict
        # assignment + thread-safe itertools.count) because touch() sits
        # on every query's device_arrays access.
        self._touch: Dict[tuple, int] = {}
        self._touch_seq = itertools.count(1)
        # live-allocation count per (seg_uid, device) plane group — O(1)
        # last-alloc detection on release (the alternative, scanning
        # _allocs, is quadratic over bulk drop_device/close churn) and
        # the failed-build guard for _touch cleanup
        self._group_refs: Dict[tuple, int] = {}
        self.pressure_evictions = 0

    # ---------------- wiring ----------------

    def set_breaker(self, breaker) -> None:
        """Install the breaker new charged registrations bill (the Node
        wires its fielddata breaker here; None disables charging)."""
        with self._lock:
            self._breaker = breaker

    @property
    def breaker(self):
        return self._breaker

    # ---------------- the write path ----------------

    def touch(self, segment, device=None) -> None:
        """Record query-time use of one segment's device residency — the
        recency signal LRU pressure eviction orders by. Lock-free (hot
        path): GIL-atomic dict write + thread-safe counter."""
        uid = getattr(segment, "uid", None)
        if uid is None:
            return
        # GIL-atomic single dict store + itertools.count (thread-safe in
        # CPython); readers (_evict_lru) snapshot under the ledger lock
        # and tolerate a stale recency value by design
        self._touch[(uid, _device_key(device))] = next(self._touch_seq)  # oslint: disable=OSL703 -- documented lock-free hot path

    def _evict_lru(self, breaker, exclude_uid) -> bool:
        """Evict the least-recently-used evictable segment-plane group
        charged to `breaker` (skipping `exclude_uid`, the tenant being
        built). Returns True when a group's evictor actually released
        residency. Caller holds the ledger lock (RLock — the evictor's
        releases re-enter it). Known coarseness: the victim is chosen
        per (segment, device) group but Segment.evict_device drops the
        segment's residency on EVERY device, so on multi-device hosts a
        pressure event also evicts the segment's other-device planes
        (and `bytes` below records only the chosen group's share)."""
        groups: Dict[tuple, list] = {}
        for a in self._allocs.values():
            if a.evictor is None or a.breaker is not breaker:
                continue
            if a.seg_uid is None or a.seg_uid == exclude_uid:
                continue
            groups.setdefault((a.seg_uid, a.device), []).append(a)
        # oldest-touch first; never-touched groups (built, never queried)
        # are the coldest of all
        order = sorted(groups, key=lambda k: (self._touch.get(k, 0), k[0]))
        for key in order:
            allocs = groups[key]
            evictor = None
            for a in allocs:
                evictor = a.evictor() if a.evictor is not None else None
                if evictor is not None:
                    break
            if evictor is None:
                # owner GC'd mid-flight: its finalizers release the bytes
                continue
            freed = sum(a.nbytes for a in allocs)
            if not evictor():
                continue            # owner busy building: try the next
            self.pressure_evictions += 1
            self._touch.pop(key, None)
            if METRICS.enabled:
                METRICS.counter("hbm.pressure_evictions").inc()
            if _fr.RECORDER.enabled:
                tl = _fr.current()
                if tl:
                    _fr.RECORDER.record(
                        tl, "hbm.evict_pressure", segment=allocs[0].segment,
                        bytes=freed, device=allocs[0].device)
            return True
        return False

    def register(self, kind: str, nbytes: int, *, owner=None, segment=None,
                 device=None, label: str = "",
                 charge: bool = True, evictor=None) -> Allocation:
        """Record one attributed allocation and derive its breaker charge.

        `owner`: when given, a weakref finalizer releases the allocation
        at the owner's GC (explicit `release()` earlier is fine — release
        is idempotent per allocation). `segment` may be a Segment-like
        object (name/uid extracted) or a plain string. `charge=False`
        registers an advisory tenant (tracked, never billed — compiled
        program footprints whose true HBM cost XLA owns). `evictor`: a
        bound method (held weakly) that releases this tenant's residency
        on demand — registrations carrying one become candidates for
        LRU pressure eviction.

        An over-budget charged registration first tries to make room by
        evicting least-recently-used evictable segment planes charged to
        the same breaker (ROADMAP item 2: a 1M+ doc index must LOAD
        under a fixed budget, not fail); only when nothing evictable
        remains does the breaker's CircuitBreakingException propagate —
        nothing is recorded in that case."""
        seg_name = ""
        seg_uid = None
        if segment is not None:
            if isinstance(segment, str):
                seg_name = segment
            else:
                seg_name = getattr(segment, "name", "") or ""
                seg_uid = getattr(segment, "uid", None)
        nbytes = int(nbytes)
        breaker = self._breaker if (charge and nbytes > 0) else None
        if evictor is not None and not isinstance(evictor, weakref.ref):
            evictor = (weakref.WeakMethod(evictor)
                       if hasattr(evictor, "__self__")
                       else weakref.ref(evictor))
        alloc = Allocation(next(self._aid), kind, nbytes, seg_name, seg_uid,
                           _device_key(device), label, breaker,
                           evictor=evictor)
        with self._lock:
            if breaker is not None:
                while True:
                    try:
                        # charge INSIDE the ledger lock: CircuitBreaker is
                        # not thread-safe (check-then-act + bare `used +=`),
                        # and the ledger is its sole mutator — serializing
                        # here is what makes the breaker↔ledger invariant
                        # exact under concurrency
                        breaker.add_estimate(nbytes,
                                             label or f"hbm[{kind}]")
                        break
                    except Exception:
                        # pressure path: drop the LRU evictable plane and
                        # retry; give up (and re-raise) when nothing is
                        # left to evict
                        if self._evict_lru(breaker, seg_uid):
                            continue
                        self.breaker_trips += 1
                        if METRICS.enabled:
                            METRICS.counter("hbm.breaker_trips").inc()
                        if _fr.RECORDER.enabled:
                            tl = _fr.current()
                            if tl:
                                _fr.RECORDER.record(tl, "hbm.breaker_trip",
                                                    tenant=kind,
                                                    bytes=nbytes,
                                                    label=label)
                        if seg_uid is not None and not self._group_refs.get(
                                (seg_uid, alloc.device)):
                            # the build's pre-registration touch
                            # (Segment.device_arrays) minted a recency
                            # key for a group that never got an
                            # allocation — without this, sustained
                            # nothing-evictable pressure leaks a _touch
                            # entry per failed build forever (release
                            # cleanup only fires for groups that lived)
                            self._touch.pop((seg_uid, alloc.device), None)
                        raise
            self._allocs[alloc.aid] = alloc
            if seg_uid is not None:
                gk = (seg_uid, alloc.device)
                self._group_refs[gk] = self._group_refs.get(gk, 0) + 1
            self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes
            self._peak_by_kind[kind] = max(
                self._peak_by_kind.get(kind, 0), self._by_kind[kind])
            self._total += nbytes
            self._peak = max(self._peak, self._total)
            self.registrations += 1
            if breaker is not None:
                ent = self._charged.setdefault(id(breaker), [breaker, 0])
                ent[1] += nbytes
            gauge_total = self._total
            gauge_kind = self._by_kind.get(kind, 0)
        if METRICS.enabled:
            METRICS.gauge("hbm.ledger.total_bytes").set(gauge_total)
            METRICS.gauge(f"hbm.ledger.{kind}.bytes").set(gauge_kind)
        if _fr.RECORDER.enabled:
            tl = _fr.current()
            if tl:
                _fr.RECORDER.record(tl, "hbm.build", tenant=kind,
                                    bytes=nbytes, segment=seg_name,
                                    label=label)
        if owner is not None:
            weakref.finalize(owner, self._release_id, alloc.aid)
        return alloc

    def release(self, alloc: Optional[Allocation]) -> None:
        """Release one allocation: subtract its bytes and credit the
        breaker it was charged to. Idempotent — the weakref backstop and
        an explicit release can both fire."""
        if alloc is None:
            return
        self._release_id(alloc.aid)

    def _release_id(self, aid: int) -> None:
        with self._lock:
            alloc = self._allocs.pop(aid, None)
            if alloc is None or not alloc.live:
                return
            alloc.live = False
            self._by_kind[alloc.kind] = \
                self._by_kind.get(alloc.kind, 0) - alloc.nbytes
            self._total -= alloc.nbytes
            self.releases += 1
            if alloc.seg_uid is not None:
                gk = (alloc.seg_uid, alloc.device)
                n = self._group_refs.get(gk, 1) - 1
                if n <= 0:
                    # last allocation of this (segment, device) plane
                    # group: drop its LRU recency key too, or merge/
                    # refresh churn (every merge mints a new uid) leaks
                    # _touch entries in the process-singleton forever
                    self._group_refs.pop(gk, None)
                    self._touch.pop(gk, None)
                else:
                    self._group_refs[gk] = n
            if alloc.breaker is not None:
                ent = self._charged.get(id(alloc.breaker))
                if ent is not None:
                    ent[1] -= alloc.nbytes
                    # charged allocations always have nbytes > 0, so a
                    # zero balance already means no live charges remain
                    if ent[1] <= 0:
                        del self._charged[id(alloc.breaker)]
                # credit inside the lock — the ledger is the breaker's
                # sole mutator (see register)
                alloc.breaker.release(alloc.nbytes)
            gauge_total = self._total
            gauge_kind = self._by_kind.get(alloc.kind, 0)
        if METRICS.enabled:
            METRICS.gauge("hbm.ledger.total_bytes").set(gauge_total)
            METRICS.gauge(f"hbm.ledger.{alloc.kind}.bytes").set(gauge_kind)
        if _fr.RECORDER.enabled:
            tl = _fr.current()
            if tl:
                _fr.RECORDER.record(tl, "hbm.evict", tenant=alloc.kind,
                                    bytes=alloc.nbytes,
                                    segment=alloc.segment,
                                    label=alloc.label)

    # ---------------- reads ----------------

    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self) -> dict:
        """Rollup for `_nodes/stats` "hbm" and the bench `extra.hbm`
        stamp: totals, peaks, and per-tenant-kind bytes/peaks/counts."""
        with self._lock:
            counts: Dict[str, int] = {}
            charged = 0
            for a in self._allocs.values():
                counts[a.kind] = counts.get(a.kind, 0) + 1
                if a.charged:
                    charged += a.nbytes
            tenants = {
                k: {"bytes": self._by_kind.get(k, 0),
                    "peak_bytes": self._peak_by_kind.get(k, 0),
                    "count": counts.get(k, 0)}
                for k in sorted(set(self._by_kind) | set(counts))
                if self._by_kind.get(k, 0) or counts.get(k, 0)
                or self._peak_by_kind.get(k, 0)}
            return {"total_bytes": self._total,
                    "peak_bytes": self._peak,
                    "charged_bytes": charged,
                    "allocations": len(self._allocs),
                    "registrations": self.registrations,
                    "releases": self.releases,
                    "breaker_trips": self.breaker_trips,
                    "pressure_evictions": self.pressure_evictions,
                    "tenants": tenants}

    def peak_stamp(self) -> dict:
        """The BENCH-json `extra.hbm` stamp (bench.py and
        scripts/measure_concurrency.py both emit it): current + peak
        totals and peak bytes by tenant kind — the committed footprint
        baseline ROADMAP item 1 must beat."""
        snap = self.snapshot()
        return {"total_bytes": snap["total_bytes"],
                "peak_bytes": snap["peak_bytes"],
                "peak_by_kind": {k: t["peak_bytes"]
                                 for k, t in snap["tenants"].items()
                                 if t["peak_bytes"]}}

    def top_tenants(self, limit: int = 10) -> List[dict]:
        """Largest live allocations, for `scripts/hbm_report.py`."""
        with self._lock:
            allocs = sorted(self._allocs.values(),
                            key=lambda a: (-a.nbytes, a.aid))[:limit]
            return [{"kind": a.kind, "bytes": a.nbytes,
                     "segment": a.segment, "device": a.device,
                     "label": a.label} for a in allocs]

    def segment_residency(self) -> Dict[Any, dict]:
        """Per-segment device residency: keyed by segment uid when known
        (stable across same-named segments of different indices), else
        name — the `GET /_cat/segments` columns."""
        out: Dict[Any, dict] = {}
        with self._lock:
            for a in self._allocs.values():
                if not a.segment and a.seg_uid is None:
                    continue
                key = a.seg_uid if a.seg_uid is not None else a.segment
                ent = out.setdefault(key, {"segment": a.segment,
                                           "total_bytes": 0, "kinds": {}})
                ent["total_bytes"] += a.nbytes
                ent["kinds"][a.kind] = ent["kinds"].get(a.kind, 0) + a.nbytes
        return out

    # ---------------- invariants + silicon cross-check ----------------

    def verify_breakers(self) -> List[str]:
        """The standing ledger↔breaker invariant: for every breaker with
        (ever-unreleased) charges, the sum of live charged bytes must
        equal `breaker.used`. Returns human-readable mismatches (empty =
        healthy); asserted after every tier-1 test by a conftest
        fixture."""
        problems: List[str] = []
        with self._lock:
            entries = [(b, n) for (b, n) in self._charged.values()]
        for breaker, ledger_bytes in entries:
            used = getattr(breaker, "used", None)
            if used is None:
                continue
            if int(used) != int(ledger_bytes):
                problems.append(
                    f"breaker[{getattr(breaker, 'name', '?')}] used="
                    f"{used} but ledger holds {ledger_bytes} charged "
                    f"bytes")
        return problems

    def check_device(self, device=None,
                     threshold: float = 0.25) -> Optional[dict]:
        """On real silicon, cross-check the ledger total against the
        device allocator (`device.memory_stats()["bytes_in_use"]`).
        Drift beyond `threshold` (fraction of bytes_in_use, floor 64 MiB
        — XLA holds scratch/program memory the ledger deliberately does
        not model) triggers a flight-recorder `hbm_drift` dump, rate
        limited to one per 60s: callers include every `_nodes/stats`
        poll, and sustained drift must not churn useful anomaly dumps
        out of the bounded store. Returns the comparison, or None when
        the backend exposes no stats (CPU)."""
        import time as _time

        import jax
        if device is None:
            devices = jax.devices()
            if not devices:
                return None
            device = devices[0]
        stats_fn = getattr(device, "memory_stats", None)
        if stats_fn is None:
            return None
        try:
            stats = stats_fn()
        except Exception:
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        in_use = int(stats["bytes_in_use"])
        ledger = self.total_bytes()
        drift = abs(in_use - ledger)
        floor = int(os.environ.get("OPENSEARCH_TPU_HBM_DRIFT_FLOOR",
                                   64 << 20))
        limit = max(int(in_use * threshold), floor)
        out = {"device": str(device), "bytes_in_use": in_use,
               "ledger_bytes": ledger, "drift_bytes": drift,
               "drift_limit": limit, "ok": drift <= limit}
        with self._lock:
            self.drift_checks += 1
        if not out["ok"]:
            now = _time.monotonic()
            with self._lock:
                dump = now - self._last_drift_dump >= 60.0
                if dump:
                    self._last_drift_dump = now
                    self.drift_dumps += 1
            if dump and _fr.RECORDER.enabled:
                _fr.RECORDER.trigger(
                    "hbm_drift", [_fr.current()] if _fr.current() else None,
                    note=f"ledger {ledger}B vs device {in_use}B "
                         f"(drift {drift}B > {limit}B)", force=True)
        return out

    # ---------------- test/bench isolation ----------------

    def reset(self) -> None:
        """Release every live allocation (crediting breakers) and zero
        the peaks — isolation hook for bench cells and tests, mirroring
        `MetricsRegistry.reset`. Owners' weakref finalizers firing later
        are no-ops (release is idempotent per allocation)."""
        with self._lock:
            aids = list(self._allocs)
        for aid in aids:
            self._release_id(aid)
        with self._lock:
            self._peak = self._total
            self._peak_by_kind = {k: v for k, v in self._by_kind.items()
                                  if v}
            self.registrations = 0
            self.releases = 0
            self.breaker_trips = 0
            self.pressure_evictions = 0
            self._touch = {}
            self._group_refs = {}


# process-default ledger (one node per process, like TRACER/METRICS)
LEDGER = HBMLedger()
