"""Query insights: workload fingerprinting + heavy-hitter attribution.

The observatory stack (telemetry PR 3, flight recorder PR 6, cost
accounting PR 7, fleet SLOs PR 10) can say *that* a lane's latency SLO
is burning, *which* node is slow and *how many* bytes a query moved —
but nothing could say *which queries* are responsible. This module
closes that gap, the reference analog of the query-insights plugin
(top-N queries by latency/cost, grouped by query shape): every search is
fingerprinted into a bounded query *shape*, per-shape rolling aggregates
ride a fixed-capacity heavy-hitter sketch, and the result federates
cluster-wide and feeds SLO-burn forensics — the attribution input the
ROADMAP item-1 load-shed actuator needs ("shed batch-lane load" is only
actionable when the engine can name the load).

Design constraints:

- **Fingerprints carry structure, never text.** A shape is the
  normalized DSL skeleton (query-node kinds + field names, values
  stripped) plus coarse features (term count, agg kinds, sort kind,
  size bucket, lane). Raw query/body strings never land in a
  fingerprint feature, a metric label, or a wire payload — oslint
  OSL602 enforces the label half statically.
- **Memory is O(capacity), not O(workload cardinality).** Per-shape
  aggregates live in a space-saving (Misra-Gries-family) sketch: at
  most `capacity` monitored shapes, eviction by minimum estimated
  count. The classic guarantees hold (N records, capacity c):
  every monitored shape reports `true <= est <= true + error` with
  `error <= N/c`, and any shape with true frequency > N/c is
  monitored. A 10k-distinct-shape workload costs the same bytes as a
  10-shape one. The recent-activity window is a `deque(maxlen=...)`
  ring (OSL602's bounded-growth discipline).
- **Merge is commutative.** Federation (`GET /_insights/top_queries`
  on a cluster) merges per-node sketch wires: counts and errors sum
  over the key union, latency sketches merge bin-wise through the
  DDSketch algebra `utils/metrics.py` proved for `_cluster/stats`,
  and a key absent from a *full* wire adds that wire's minimum count
  to the merged error (absence from a non-full sketch means a true
  zero). Union + sum is order-free; the final truncation to capacity
  uses the deterministic (count desc, key asc) order — so any member
  can coordinate and every coordinator answers identically.
- **The hot path is one lock + O(1) dict ops.** Recording at the
  `Node.search` boundary takes the sketch lock for a dict upsert;
  eviction's O(capacity) min-scan only runs when a NEW shape arrives
  at a full sketch. Disabled (`OPENSEARCH_TPU_INSIGHTS=0`) the
  per-search cost is one attribute read (the flight-recorder
  discipline; tests pin the guard).

Attribution loop (docs/OBSERVABILITY.md "Query insights"):

- an `slo.burn` alert carries the top-K fingerprints active in the
  offending window (obs/slo.py enriches its dump bundle),
- each top-query entry links its WORST flight-recorder timeline id,
- slowlog entries carry the request's fingerprint,
- `/_metrics` exports only the top-K (labels are the shape hash).
"""

from __future__ import annotations

import contextvars
import hashlib
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.metrics import sketch_percentile

__all__ = ["fingerprint", "SpaceSavingSketch", "merge_wires",
           "QueryInsights", "INSIGHTS", "begin", "finish", "current",
           "note_bytes", "note_blocks", "note_escalation",
           "note_cache_hit", "note_rejection_source"]

TOP_BY = ("latency", "count", "bytes")

# shape-walk guards: a hostile/degenerate body must cost bounded work
_MAX_DEPTH = 12
_MAX_CHILDREN = 24
_MAX_SHAPE_LEN = 512

# query kinds whose spec is {field: value-ish}: the field name is
# structure, the value is stripped; match-ish kinds contribute a term
# count (whitespace tokens of the value — a count, never the text)
_FIELD_KINDS = frozenset((
    "match", "match_phrase", "match_phrase_prefix", "match_bool_prefix",
    "term", "terms", "prefix", "wildcard", "regexp", "fuzzy", "range",
    "rank_feature", "distance_feature", "geo_distance", "geo_shape",
    "geo_bounding_box", "intervals", "span_term", "knn",
    "neural_sparse"))
_TERMY_KINDS = frozenset((
    "match", "match_phrase", "match_phrase_prefix", "match_bool_prefix"))
_COMPOUND_LIST_KEYS = ("must", "should", "must_not", "filter")


def _term_count(v) -> int:
    if isinstance(v, str):
        return len(v.split())
    if isinstance(v, dict):
        q = v.get("query")
        if isinstance(q, str):
            return len(q.split())
        return 1
    if isinstance(v, (list, tuple)):
        return len(v)
    return 1


class _ShapeStats:
    __slots__ = ("terms", "depth", "clauses")

    def __init__(self):
        self.terms = 0
        self.depth = 0
        self.clauses = 0


def _shape_node(node, depth: int, st: _ShapeStats) -> str:
    """Normalized skeleton of one query node: kind names and field
    names survive, every value is stripped. Bounded depth/fan-out."""
    if depth > _MAX_DEPTH or not isinstance(node, dict) or not node:
        return "?"
    st.depth = max(st.depth, depth)
    kind = sorted(node)[0] if len(node) > 1 else next(iter(node))
    spec = node.get(kind)
    st.clauses += 1
    if kind == "bool" and isinstance(spec, dict):
        parts = []
        for ck in _COMPOUND_LIST_KEYS:
            sub = spec.get(ck)
            if sub is None:
                continue
            subs = sub if isinstance(sub, list) else [sub]
            inner = ",".join(_shape_node(s, depth + 1, st)
                             for s in subs[:_MAX_CHILDREN])
            parts.append(f"{ck}:[{inner}]")
        return f"bool({','.join(parts)})"
    if kind in ("dis_max", "hybrid") and isinstance(spec, dict):
        subs = spec.get("queries") or []
        inner = ",".join(_shape_node(s, depth + 1, st)
                         for s in subs[:_MAX_CHILDREN])
        return f"{kind}([{inner}])"
    if kind in ("nested", "constant_score", "function_score",
                "script_score", "boosting") and isinstance(spec, dict):
        sub = (spec.get("query") or spec.get("positive"))
        inner = _shape_node(sub, depth + 1, st) if sub else ""
        return f"{kind}({inner})"
    if kind in ("multi_match", "combined_fields", "query_string",
                "simple_query_string") and isinstance(spec, dict):
        fields = spec.get("fields")
        nf = len(fields) if isinstance(fields, list) else 1
        st.terms += _term_count(spec)
        return f"{kind}(fields:{nf})"
    if kind in _FIELD_KINDS and isinstance(spec, dict) and spec:
        field = sorted(spec)[0]
        if kind in _TERMY_KINDS:
            st.terms += _term_count(spec[field])
        elif kind == "terms" and isinstance(spec.get(field),
                                            (list, tuple)):
            st.terms += len(spec[field])
        else:
            st.terms += 1
        return f"{kind}({field})"
    return kind


def _agg_kinds(aggs, depth: int = 0) -> List[str]:
    out: List[str] = []
    if not isinstance(aggs, dict) or depth > 4:
        return out
    for spec in aggs.values():
        if not isinstance(spec, dict):
            continue
        kinds = [k for k in spec if k not in ("aggs", "aggregations")]
        out.extend(sorted(kinds)[:2])
        sub = spec.get("aggs", spec.get("aggregations"))
        if sub:
            out.extend(_agg_kinds(sub, depth + 1))
    return out[:8]


def _sort_kind(body: dict) -> str:
    sort = body.get("sort")
    if not sort:
        return "score"
    fields = []
    for s in (sort if isinstance(sort, list) else [sort]):
        f = s if isinstance(s, str) else (next(iter(s))
                                          if isinstance(s, dict) and s
                                          else "?")
        fields.append("score" if f == "_score" else "field")
    return "+".join(fields[:3]) or "score"


def _size_bucket(body: dict) -> int:
    try:
        size = int(body.get("size", 10))
    except (TypeError, ValueError):
        return 10
    b = 1
    while b < max(size, 1) and b < 65536:
        b <<= 1
    return b


def fingerprint(body: dict, lane: str = "interactive"
                ) -> Tuple[str, str, dict]:
    """-> (key, shape, features): the bounded identity of one search
    body. `key` is a 12-hex digest (the only thing metric labels ever
    carry), `shape` the normalized value-free DSL skeleton, `features`
    the coarse workload descriptors. Never raises — an unparseable
    body fingerprints as the "unparseable" shape."""
    try:
        st = _ShapeStats()
        q = body.get("query") if isinstance(body, dict) else None
        shape = (_shape_node(q, 1, st) if isinstance(q, dict)
                 else "match_all")[:_MAX_SHAPE_LEN]
        aggs = _agg_kinds(body.get("aggs", body.get("aggregations")))
        sort = _sort_kind(body)
        size_b = _size_bucket(body)
        knn = bool(body.get("knn"))
        # term COUNT rides the identity as a pow2 bucket: a 1-term and
        # a 30-term match are different workloads (BM25S: eager-scoring
        # wins are term-count-dependent) but the bucket keeps identity
        # cardinality bounded. depth/clauses are fully determined by
        # the shape string and need no separate canon slot.
        terms_b = 1
        while terms_b < max(st.terms, 1) and terms_b < 256:
            terms_b <<= 1
        # vector/hybrid workload descriptors (ISSUE 15): a hybrid body
        # carries its sub-query COUNT and the set of retrieval-family
        # kinds as identity — a 2-sub lexical+knn hybrid and a 3-sub
        # hybrid with learned-sparse are different workloads the
        # heavy-hitter attribution (and the PR-14 remediator's shed
        # match) must tell apart. knn also derives from the QUERY tree
        # (query.knn / a knn sub-query), not just the ES-style body key.
        sub_kinds: List[str] = []
        hybrid_n = 0
        if isinstance(q, dict) and isinstance(q.get("hybrid"), dict):
            subs = q["hybrid"].get("queries")
            if isinstance(subs, list):
                hybrid_n = len(subs)
                sub_kinds = sorted({next(iter(s)) for s in
                                    subs[:_MAX_CHILDREN]
                                    if isinstance(s, dict) and s})[:8]
        # the FEATURE flag derives from every vector form (ES-style
        # body key, query.knn, knn sub-queries) — but the CANON slot
        # keeps only the body-key bit it always carried: query.knn and
        # hybrid sub-kinds are already identity-bearing via the shape
        # string / the hybrid suffix below, and re-deriving the canon
        # flag would change every pre-existing query.knn digest
        knn_feature = knn or "knn(" in shape or "knn" in sub_kinds
        features = {"kind": shape.split("(", 1)[0], "terms": st.terms,
                    "terms_bucket": terms_b, "depth": st.depth,
                    "clauses": st.clauses, "aggs": aggs, "sort": sort,
                    "size_bucket": size_b, "lane": lane,
                    "knn": knn_feature,
                    "hybrid": hybrid_n > 0, "sub_queries": hybrid_n,
                    "sub_kinds": sub_kinds}
        canon = (f"{shape}|lane={lane}|sort={sort}|"
                 f"aggs={','.join(aggs)}|size={size_b}|knn={int(knn)}|"
                 f"terms={terms_b}")
        if hybrid_n:
            # appended ONLY for hybrid bodies so every pre-existing
            # shape digest stays stable across the format rev
            canon += f"|hybrid={hybrid_n}|subs={','.join(sub_kinds)}"
    except Exception:       # noqa: BLE001 — fingerprinting must never
        # fail a search; a pathological body lands in one bucket
        shape, features = "unparseable", {"kind": "unparseable",
                                          "lane": lane}
        canon = f"unparseable|lane={lane}"
    key = hashlib.sha1(canon.encode("utf-8", "replace")).hexdigest()[:12]
    return key, shape, features


# ---------------------------------------------------------------------
# the space-saving heavy-hitter sketch
# ---------------------------------------------------------------------

class _Entry:
    __slots__ = ("key", "shape", "features", "count", "error",
                 "lat_bins", "lat_count", "lat_sum_ms", "bytes_moved",
                 "blocks_total", "blocks_skipped", "escalations",
                 "cache_hits", "rejections", "errors", "worst_ms",
                 "worst_timeline", "first_seen_mono", "last_seen_mono")

    def __init__(self, key: str, shape: str, features: dict,
                 count: int, error: int, now: float):
        self.key = key
        self.shape = shape
        self.features = features
        self.count = count
        self.error = error
        self.lat_bins: Dict[int, int] = {}
        self.lat_count = 0
        self.lat_sum_ms = 0.0
        self.bytes_moved = 0
        self.blocks_total = 0
        self.blocks_skipped = 0
        self.escalations = 0
        self.cache_hits = 0
        self.rejections = 0
        self.errors = 0
        self.worst_ms = 0.0
        self.worst_timeline = 0
        self.first_seen_mono = now
        self.last_seen_mono = now


def _lat_snapshot(bins: Dict[int, int], count: int,
                  sum_ms: float) -> dict:
    out = {"count": count, "sum_ms": round(sum_ms, 3)}
    for p in (50, 95, 99):
        v = sketch_percentile(bins, count, p)
        out[f"p{p}_ms"] = round(v, 4) if v is not None else None
    return out


class SpaceSavingSketch:
    """Fixed-capacity heavy-hitter summary with per-key rolling
    aggregates. Counts carry the space-saving bounds; the aggregates
    (latency sketch, bytes, skip/escalation/cache/rejection tallies)
    are per-tenure — an evicted-and-readopted shape restarts them,
    which is the honest bounded-memory trade and is documented on the
    wire (`error` prices the count uncertainty)."""

    def __init__(self, capacity: int):
        if capacity < 2:
            raise ValueError("sketch capacity must be >= 2")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self.total_records = 0
        self.evictions = 0

    def record(self, key: str, shape: str, features: dict,
               latency_ms: Optional[float] = None,
               bytes_moved: int = 0, blocks_total: int = 0,
               blocks_skipped: int = 0, escalations: int = 0,
               cache_hit: bool = False, rejected: bool = False,
               error: bool = False, timeline_id: int = 0) -> None:
        now = time.monotonic()
        lat_bin = None
        if latency_ms is not None:
            from ..ops.aggs import ddsketch_bin
            lat_bin = ddsketch_bin(float(latency_ms))
        with self._lock:
            self.total_records += 1
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self.capacity:
                    victim = min(self._entries.values(),
                                 key=lambda v: (v.count, v.key))
                    self._entries.pop(victim.key)
                    self.evictions += 1
                    e = _Entry(key, shape, features,
                               victim.count + 1, victim.count, now)
                else:
                    e = _Entry(key, shape, features, 1, 0, now)
                self._entries[key] = e
            else:
                e.count += 1
            e.last_seen_mono = now
            if lat_bin is not None:
                e.lat_bins[lat_bin] = e.lat_bins.get(lat_bin, 0) + 1
                e.lat_count += 1
                e.lat_sum_ms += float(latency_ms)
                if float(latency_ms) >= e.worst_ms:
                    e.worst_ms = float(latency_ms)
                    if timeline_id:
                        e.worst_timeline = int(timeline_id)
            e.bytes_moved += int(bytes_moved)
            e.blocks_total += int(blocks_total)
            e.blocks_skipped += int(blocks_skipped)
            e.escalations += int(escalations)
            if cache_hit:
                e.cache_hits += 1
            if rejected:
                e.rejections += 1
            if error:
                e.errors += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def min_count(self) -> int:
        with self._lock:
            if not self._entries:
                return 0
            return min(e.count for e in self._entries.values())

    def meta_for(self, keys) -> Dict[str, tuple]:
        """key -> (shape, features, worst_timeline) for the monitored
        subset of `keys` — the windowed read path's metadata join,
        O(|keys|) under the lock instead of a full wire serialization."""
        with self._lock:
            out = {}
            for k in keys:
                e = self._entries.get(k)
                if e is not None:
                    out[k] = (e.shape, dict(e.features),
                              e.worst_timeline)
            return out

    @property
    def full(self) -> bool:
        with self._lock:
            return len(self._entries) >= self.capacity

    def _serialize(self, e: _Entry) -> dict:
        return {"fingerprint": e.key, "shape": e.shape,
                "features": dict(e.features),
                "count": e.count, "error": e.error,
                "latency": {"bins": {str(b): c
                                     for b, c in sorted(e.lat_bins.items())},
                            "count": e.lat_count,
                            "sum_ms": round(e.lat_sum_ms, 3)},
                "bytes_moved": e.bytes_moved,
                "blocks_total": e.blocks_total,
                "blocks_skipped": e.blocks_skipped,
                "escalations": e.escalations,
                "cache_hits": e.cache_hits,
                "rejections": e.rejections,
                "errors": e.errors,
                "worst_ms": round(e.worst_ms, 3),
                "worst_timeline": e.worst_timeline}

    def to_wire(self) -> dict:
        """JSON-safe federation payload (the `/_internal/insights`
        answer). `full` + `min_count` let the merge price absence
        correctly: a key absent from a full sketch may have true count
        up to that sketch's minimum."""
        with self._lock:
            entries = [self._serialize(e)
                       for e in self._entries.values()]
            full = len(self._entries) >= self.capacity
            mn = (min(e.count for e in self._entries.values())
                  if self._entries else 0)
            total = self.total_records
        entries.sort(key=lambda d: (-d["count"], d["fingerprint"]))
        return {"capacity": self.capacity, "total_records": total,
                "full": full, "min_count": mn, "entries": entries}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self.total_records = 0
            self.evictions = 0


def _derived(d: dict) -> dict:
    """Attach read-side derivations to a serialized entry: latency
    percentiles from the bins, mean bytes/query, block-skip rate."""
    out = dict(d)
    lat = d.get("latency") or {}
    bins = {int(b): int(c) for b, c in (lat.get("bins") or {}).items()}
    out["latency"] = _lat_snapshot(bins, int(lat.get("count", 0)),
                                   float(lat.get("sum_ms", 0.0)))
    cnt = max(int(d.get("count", 0)), 1)
    out["mean_bytes_per_query"] = round(d.get("bytes_moved", 0) / cnt, 1)
    bt = int(d.get("blocks_total", 0))
    out["block_skip_rate"] = (round(d.get("blocks_skipped", 0) / bt, 4)
                              if bt else None)
    return out


def merge_wires(wires: Sequence[dict], capacity: int) -> dict:
    """Commutative merge of sketch wires: counts/errors/aggregates sum
    over the key union, latency bins add bin-wise (the DDSketch merge
    algebra), and a key absent from a FULL wire adds that wire's
    `min_count` to the merged error (its true count there is unknown
    but bounded by the minimum; absence from a non-full sketch is a
    true zero). The result truncates to `capacity` by the
    deterministic (count desc, key asc) order, so coordinator choice
    and scrape arrival order can never change the answer."""
    merged: Dict[str, dict] = {}
    metas = []
    for w in wires:
        if not isinstance(w, dict):
            continue
        metas.append((bool(w.get("full")), int(w.get("min_count", 0)),
                      {e["fingerprint"] for e in w.get("entries", [])}))
        for e in w.get("entries", []):
            k = e["fingerprint"]
            m = merged.get(k)
            if m is None:
                m = {"fingerprint": k, "shape": e.get("shape", ""),
                     "features": dict(e.get("features") or {}),
                     "count": 0, "error": 0,
                     "latency": {"bins": {}, "count": 0, "sum_ms": 0.0},
                     "bytes_moved": 0, "blocks_total": 0,
                     "blocks_skipped": 0, "escalations": 0,
                     "cache_hits": 0, "rejections": 0, "errors": 0,
                     "worst_ms": 0.0, "worst_timeline": 0}
                merged[k] = m
            m["count"] += int(e.get("count", 0))
            m["error"] += int(e.get("error", 0))
            lat, elat = m["latency"], e.get("latency") or {}
            for b, c in (elat.get("bins") or {}).items():
                lat["bins"][b] = lat["bins"].get(b, 0) + int(c)
            lat["count"] += int(elat.get("count", 0))
            lat["sum_ms"] = round(lat["sum_ms"]
                                  + float(elat.get("sum_ms", 0.0)), 3)
            for f in ("bytes_moved", "blocks_total", "blocks_skipped",
                      "escalations", "cache_hits", "rejections",
                      "errors"):
                m[f] += int(e.get(f, 0))
            # tuple compare keeps the merge commutative even when two
            # wires tie on worst_ms (the timeline id breaks the tie
            # deterministically)
            cand = (float(e.get("worst_ms", 0.0)),
                    int(e.get("worst_timeline") or 0))
            if cand > (m["worst_ms"], m["worst_timeline"]):
                m["worst_ms"], m["worst_timeline"] = cand
    # absence pricing: a full wire that does not monitor k may hold up
    # to its min_count occurrences of k — widen the error bound
    for k, m in merged.items():
        for full, mn, keys in metas:
            if full and k not in keys:
                m["error"] += mn
    out = sorted(merged.values(),
                 key=lambda d: (-d["count"], d["fingerprint"]))
    total = sum(int(w.get("total_records", 0)) for w in wires
                if isinstance(w, dict))
    return {"capacity": int(capacity), "total_records": total,
            "full": len(out) > capacity,
            "min_count": (out[-1]["count"] if out else 0),
            "entries": out[: int(capacity)]}


def merge_windowed_wires(wires: Sequence[dict], capacity: int,
                         window_s: float) -> dict:
    """Commutative merge of WINDOWED wires (exact ring aggregates):
    counts, latency sums and bytes add per key; shape metadata comes
    from whichever member still monitors the key. Same deterministic
    truncation order as `merge_wires`."""
    merged: Dict[str, dict] = {}
    for w in wires:
        if not isinstance(w, dict):
            continue
        for e in w.get("entries", []):
            k = e["fingerprint"]
            m = merged.get(k)
            if m is None:
                m = {"fingerprint": k, "count": 0,
                     "latency_sum_ms": 0.0, "max_ms": 0.0,
                     "bytes_moved": 0, "shape": e.get("shape", ""),
                     "worst_timeline": 0}
                merged[k] = m
            m["count"] += int(e.get("count", 0))
            m["latency_sum_ms"] = round(
                m["latency_sum_ms"] + float(e.get("latency_sum_ms",
                                                  0.0)), 3)
            # the worst-timeline link must follow the worst LATENCY
            # (tuple compare: commutative even on max_ms ties), or a
            # federated windowed entry could link a fast node's journal
            cand = (float(e.get("max_ms", 0.0)),
                    int(e.get("worst_timeline") or 0))
            if cand > (m["max_ms"], int(m["worst_timeline"] or 0)):
                m["max_ms"], m["worst_timeline"] = cand
            m["bytes_moved"] += int(e.get("bytes_moved", 0))
            if m["shape"] in ("", "(evicted)") and e.get("shape"):
                m["shape"] = e["shape"]
    out = sorted(merged.values(),
                 key=lambda d: (-d["count"], d["fingerprint"]))
    for m in out:
        m["latency_mean_ms"] = round(
            m["latency_sum_ms"] / max(m["count"], 1), 3)
    return {"capacity": int(capacity), "windowed": True,
            "window_s": float(window_s),
            "total_records": sum(m["count"] for m in out),
            "full": False, "min_count": 0,
            "entries": out[: int(capacity)]}


# ---------------------------------------------------------------------
# the per-request observation (contextvar, the query_cost pattern)
# ---------------------------------------------------------------------

class Observation:
    """One search's in-flight attribution state. Taps along the path
    (cache hit, bytes moved, block skips, escalations, rejection
    source) annotate it; the search boundary records it once."""

    __slots__ = ("key", "shape", "features", "lane", "cache_hit",
                 "bytes_moved", "blocks_total", "blocks_skipped",
                 "escalations", "rejected_by")

    def __init__(self, key: str, shape: str, features: dict, lane: str):
        self.key = key
        self.shape = shape
        self.features = features
        self.lane = lane
        self.cache_hit = False
        self.bytes_moved = 0
        self.blocks_total = 0
        self.blocks_skipped = 0
        self.escalations = 0
        self.rejected_by: Optional[str] = None


_current: contextvars.ContextVar = contextvars.ContextVar(
    "opensearch_tpu_insights_obs", default=None)


def current() -> Optional[Observation]:
    return _current.get()


def begin(body: dict, lane: str = "interactive") -> tuple:
    """Install a fresh observation; returns (obs, token) for the
    paired `finish`. A no-op pair (None, None) when disabled."""
    if not INSIGHTS.enabled:
        return None, None
    key, shape, features = fingerprint(body, lane)
    obs = Observation(key, shape, features, lane)
    return obs, _current.set(obs)


def finish(token, obs: Optional[Observation],
           latency_ms: Optional[float] = None,
           rejected: bool = False, error: bool = False,
           timeline_id: int = 0) -> None:
    """Uninstall and record the observation into the engine."""
    if token is not None:
        _current.reset(token)
    if obs is None or not INSIGHTS.enabled:
        return
    INSIGHTS.record_observation(obs, latency_ms=latency_ms,
                                rejected=rejected or
                                obs.rejected_by is not None,
                                error=error, timeline_id=timeline_id)


def note_bytes(n: int) -> None:
    obs = _current.get()
    if obs is not None:
        obs.bytes_moved += int(n)


def note_blocks(total: int, skipped: int) -> None:
    obs = _current.get()
    if obs is not None:
        obs.blocks_total += int(total)
        obs.blocks_skipped += int(skipped)


def note_escalation() -> None:
    obs = _current.get()
    if obs is not None:
        obs.escalations += 1


def note_cache_hit() -> None:
    obs = _current.get()
    if obs is not None:
        obs.cache_hit = True


def note_rejection_source(source: str) -> None:
    obs = _current.get()
    if obs is not None:
        obs.rejected_by = source


# ---------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------

class QueryInsights:
    """Process-singleton insights engine: the sketch, the bounded
    recent-activity ring (windowed queries), and the read surfaces."""

    def __init__(self, capacity: Optional[int] = None,
                 window_capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        env = os.environ
        self.capacity = int(
            capacity if capacity is not None
            else env.get("OPENSEARCH_TPU_INSIGHTS_CAPACITY", 256))
        self.window_capacity = int(
            window_capacity if window_capacity is not None
            else env.get("OPENSEARCH_TPU_INSIGHTS_WINDOW_CAP", 4096))
        if enabled is None:
            v = env.get("OPENSEARCH_TPU_INSIGHTS")
            enabled = v not in ("0", "false", "no")
        self.enabled = bool(enabled)
        self.sketch = SpaceSavingSketch(self.capacity)
        # recent activity: (t_mono, key, latency_ms, bytes) — bounded
        # ring; deque.append is atomic, reads snapshot via list()
        self._recent: deque = deque(maxlen=self.window_capacity)

    # -- write side --

    def record_observation(self, obs: Observation,
                           latency_ms: Optional[float] = None,
                           rejected: bool = False, error: bool = False,
                           timeline_id: int = 0) -> None:
        if not self.enabled:
            return
        self.sketch.record(
            obs.key, obs.shape, obs.features, latency_ms=latency_ms,
            bytes_moved=obs.bytes_moved, blocks_total=obs.blocks_total,
            blocks_skipped=obs.blocks_skipped,
            escalations=obs.escalations, cache_hit=obs.cache_hit,
            rejected=rejected, error=error, timeline_id=timeline_id)
        self._recent.append((time.monotonic(), obs.key,
                             float(latency_ms or 0.0),
                             int(obs.bytes_moved)))

    def record_rejection(self, body: dict, lane: str,
                         source: str = "admission") -> None:
        """One-shot tap for rejections that never reach the search
        boundary (wlm admission 429s at the REST layer)."""
        if not self.enabled:
            return
        key, shape, features = fingerprint(body, lane)
        self.sketch.record(key, shape, features, rejected=True)
        self._recent.append((time.monotonic(), key, 0.0, 0))

    # -- read side --

    def _windowed_entries(self, window_s: float) -> List[dict]:
        cutoff = time.monotonic() - float(window_s)
        agg: Dict[str, dict] = {}
        for t, key, lat, nbytes in list(self._recent):
            if t < cutoff:
                continue
            a = agg.setdefault(key, {"fingerprint": key, "count": 0,
                                     "latency_sum_ms": 0.0,
                                     "max_ms": 0.0, "bytes_moved": 0})
            a["count"] += 1
            a["latency_sum_ms"] = round(a["latency_sum_ms"] + lat, 3)
            a["max_ms"] = max(a["max_ms"], lat)
            a["bytes_moved"] += nbytes
        meta = self.sketch.meta_for(list(agg))
        out = []
        for a in agg.values():
            m = meta.get(a["fingerprint"])
            a["latency_mean_ms"] = round(
                a["latency_sum_ms"] / max(a["count"], 1), 3)
            if m is not None:
                a["shape"], a["features"], a["worst_timeline"] = m
            else:
                a["shape"] = "(evicted)"
            out.append(a)
        return out

    @staticmethod
    def _rank_key(by: str):
        if by == "count":
            return lambda d: (-d["count"], d["fingerprint"])
        if by == "bytes":
            return lambda d: (-d.get("bytes_moved", 0), d["fingerprint"])
        # latency: total burn (sum) — "which shape costs the fleet the
        # most wall time", the blame ordering remediation wants
        return lambda d: (-(d.get("latency") or {}).get("sum_ms", 0.0)
                          if "latency" in d
                          else -d.get("latency_sum_ms", 0.0),
                          d["fingerprint"])

    def top(self, by: str = "latency", n: int = 10,
            window_s: Optional[float] = None) -> List[dict]:
        """Top-N shapes. Without a window: lifetime sketch entries with
        derived percentiles. With a window: exact aggregates over the
        bounded recent-activity ring (count/latency/bytes), joined to
        sketch metadata."""
        if by not in TOP_BY:
            raise ValueError(f"unknown top_queries ranking [{by}] "
                             f"(one of {TOP_BY})")
        if window_s is not None:
            entries = self._windowed_entries(float(window_s))
        else:
            entries = [_derived(d)
                       for d in self.sketch.to_wire()["entries"]]
        entries.sort(key=self._rank_key(by))
        return entries[: max(int(n), 0)]

    def top_fingerprints(self, window_s: float, n: int = 5) -> List[dict]:
        """The SLO-burn enrichment payload: compact top-K active in the
        window, worst-timeline linked — bounded, label-safe (hashes and
        numbers only, plus the value-free shape)."""
        out = []
        for e in self.top(by="latency", n=n, window_s=window_s):
            out.append({"fingerprint": e["fingerprint"],
                        "shape": e.get("shape", ""),
                        "count": e["count"],
                        "latency_sum_ms": e.get("latency_sum_ms", 0.0),
                        "latency_mean_ms": e.get("latency_mean_ms", 0.0),
                        "bytes_moved": e.get("bytes_moved", 0),
                        "worst_timeline": e.get("worst_timeline", 0)})
        return out

    def prometheus_top(self, n: int = 10) -> List[dict]:
        """The bounded `/_metrics` export: top-N by count, labels are
        the shape hash only (OSL602: raw query text never reaches a
        label position)."""
        if not self.enabled:
            return []
        out = []
        for e in self.top(by="count", n=n):
            out.append({"fingerprint": e["fingerprint"],
                        "count": e["count"],
                        "latency_sum_ms": e["latency"]["sum_ms"],
                        "bytes_moved": e["bytes_moved"]})
        return out

    def to_wire(self, window_s: Optional[float] = None) -> dict:
        """Federation payload. Windowed wires carry exact ring
        aggregates in the same envelope (flagged `windowed`)."""
        if window_s is None:
            return self.sketch.to_wire()
        entries = self._windowed_entries(float(window_s))
        entries.sort(key=lambda d: (-d["count"], d["fingerprint"]))
        return {"capacity": self.capacity, "windowed": True,
                "window_s": float(window_s),
                "total_records": sum(e["count"] for e in entries),
                "full": False, "min_count": 0, "entries": entries}

    def stats(self) -> dict:
        """`_nodes/stats` "insights" block."""
        return {"enabled": self.enabled,
                "capacity": self.capacity,
                "entries": len(self.sketch),
                "total_records": self.sketch.total_records,
                "evictions": self.sketch.evictions,
                "window_capacity": self.window_capacity,
                "window_events": len(self._recent)}

    def reset(self) -> None:
        """Isolation hook for tests/bench cells (the METRICS.reset
        pattern)."""
        self.sketch.reset()
        self._recent.clear()


# process-default engine (one node per process, like METRICS/RECORDER)
INSIGHTS = QueryInsights()
