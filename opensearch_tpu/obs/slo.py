"""SLO engine: declared objectives evaluated as multi-window burn rates.

The last observability gap between "survived" and "detected": the
telemetry registry holds totals, the time-series ring holds history, but
until now nothing *judged* — a chaos run was declared healthy by a human
reading a bench JSON. This module declares objectives per lane /
workload group and evaluates them continuously over sliding windows of
the time-series ring (`obs/timeseries.py`), Google-SRE style
(multiwindow, multi-burn-rate alerting: an alert fires only when BOTH a
fast and a slow window burn the error budget faster than the threshold —
the fast window gives detection latency, the slow window suppresses
blips).

The model, uniformly for every objective kind:

    bad_ratio(window)  = bad_events / total_events     over the window
    budget             = 1 - target                    (target in (0,1))
    burn_rate(window)  = bad_ratio / budget

    FIRING  iff  burn(fast) > threshold  AND  burn(slow) > threshold

Objective kinds map (lane-parameterized) onto the per-lane SLI
instrumentation `cluster/node.py` records on every search:

- ``latency``        — bad = requests whose recorded latency exceeded
  `latency_budget_ms` (counted bin-granularly from the windowed sketch
  delta); a `target` of 0.99 declares "p99 <= budget".
- ``error_rate``     — bad = `search.lane.{lane}.errors`.
- ``availability``   — bad = errors + backpressure rejections (any
  request the node failed to serve).
- ``rejection_rate`` — bad = `search.lane.{lane}.rejected` (the 429
  path; `serving.lane.{lane}.rejected` mirrors the scheduler's own).
- ``counter_ratio``  — explicit `bad_metrics` / `total_metrics` counter
  lists; the escape hatch the chaos bench uses to watch transport
  health (`dist.rpc.failed` + `dist.deadline.exhausted` per request).

A firing transition emits an ``slo.burn`` flight-recorder event carrying
the offending window's time series, freezes a dump bundle
(reason ``slo_burn``), bumps `slo.alerts_total`, and flips the
`slo.{name}.firing` gauge — visible at `GET /_slo`, in `_nodes/stats`
("slo" block) and in `/_metrics`. Resolution is the fast window dropping
back under threshold.

Every SLO MUST declare its evaluation windows (`fast_window_s`,
`slow_window_s`) — no defaults, and oslint OSL509 enforces the
declaration statically at construction sites: an objective without a
window is a dashboard, not an alert.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

from ..utils.metrics import METRICS, MetricsRegistry
from .timeseries import SAMPLER, TimeSeriesSampler

__all__ = ["SLO", "SLOEngine", "SLO_ENGINE", "default_slos", "ingest_slos"]

_KINDS = ("latency", "error_rate", "availability", "rejection_rate",
          "counter_ratio")

# at most this many points of each offending series ride an alert's
# recorder event (dumps are bounded; a 512-sample ring must not be)
_ALERT_SERIES_POINTS = 120

# top query fingerprints attached to a firing alert (obs/insights.py):
# the offending window's heaviest shapes, worst-timeline linked
_ALERT_TOP_FINGERPRINTS = 5


class SLO:
    """One declared objective. Windows are mandatory (oslint OSL509)."""

    def __init__(self, name: str, kind: str, target: float,
                 fast_window_s: float, slow_window_s: float,
                 lane: str = "interactive",
                 latency_budget_ms: Optional[float] = None,
                 burn_threshold: float = 10.0,
                 min_events: int = 1,
                 bad_metrics: Optional[Sequence[str]] = None,
                 total_metrics: Optional[Sequence[str]] = None,
                 histogram: Optional[str] = None):
        if kind not in _KINDS:
            raise ValueError(f"unknown SLO kind [{kind}] "
                             f"(one of {_KINDS})")
        if not 0.0 < float(target) < 1.0:
            raise ValueError("SLO target must be in (0, 1) — the error "
                             "budget is 1 - target")
        if not (float(fast_window_s) > 0 and float(slow_window_s) > 0):
            raise ValueError("SLO windows must be positive seconds")
        if float(fast_window_s) > float(slow_window_s):
            raise ValueError("fast window must not exceed the slow window")
        if kind == "latency" and latency_budget_ms is None:
            raise ValueError("latency SLOs need latency_budget_ms")
        if kind == "counter_ratio" and not (bad_metrics and total_metrics):
            raise ValueError("counter_ratio SLOs need bad_metrics and "
                             "total_metrics")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.lane = lane
        self.latency_budget_ms = (float(latency_budget_ms)
                                  if latency_budget_ms is not None else None)
        self.burn_threshold = float(burn_threshold)
        self.min_events = int(min_events)
        self.bad_metrics = list(bad_metrics or [])
        self.total_metrics = list(total_metrics or [])
        # explicit histogram override: a latency-kind objective over ANY
        # registry sketch (ingest SLOs window refresh-to-visible or the
        # merge-backlog depth sketch instead of a search lane)
        self.histogram = histogram

    # -- metric resolution (lane-parameterized SLI names) --

    @property
    def latency_hist(self) -> str:
        return self.histogram or f"search.lane.{self.lane}.latency_ms"

    def _lane_counter(self, leaf: str) -> str:
        return f"search.lane.{self.lane}.{leaf}"

    def tracked_histograms(self) -> List[str]:
        return [self.latency_hist] if self.kind == "latency" else []

    def series_metrics(self) -> List[str]:
        """The metrics whose windowed series ride a firing alert's
        recorder event — the forensic "what the engine saw"."""
        if self.kind == "latency":
            return [self.latency_hist]
        if self.kind == "counter_ratio":
            return list(self.bad_metrics) + list(self.total_metrics)
        out = [self._lane_counter("requests")]
        if self.kind in ("error_rate", "availability"):
            out.append(self._lane_counter("errors"))
        if self.kind in ("availability", "rejection_rate"):
            out.append(self._lane_counter("rejected"))
        return out

    def bad_total(self, sampler: TimeSeriesSampler,
                  window_s: float) -> tuple:
        """(bad, total) event counts over the window."""
        if self.kind == "latency":
            return sampler.window_over_budget(
                self.latency_hist, window_s, self.latency_budget_ms)
        if self.kind == "counter_ratio":
            bad = sum(sampler.counter_delta(m, window_s)
                      for m in self.bad_metrics)
            total = sum(sampler.counter_delta(m, window_s)
                        for m in self.total_metrics)
            return bad, total
        req = sampler.counter_delta(self._lane_counter("requests"),
                                    window_s)
        err = sampler.counter_delta(self._lane_counter("errors"), window_s)
        rej = sampler.counter_delta(self._lane_counter("rejected"),
                                    window_s)
        if self.kind == "error_rate":
            return err, req + err
        if self.kind == "availability":
            return err + rej, req + err + rej
        return rej, req + rej                     # rejection_rate

    def burn(self, sampler: TimeSeriesSampler, window_s: float) -> dict:
        bad, total = self.bad_total(sampler, window_s)
        ratio = (bad / total) if total else 0.0
        budget = 1.0 - self.target
        return {"window_s": window_s, "bad": int(bad), "total": int(total),
                "bad_ratio": round(ratio, 6),
                "burn_rate": round(ratio / budget, 4) if budget else 0.0}

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target,
               "lane": self.lane,
               "fast_window_s": self.fast_window_s,
               "slow_window_s": self.slow_window_s,
               "burn_threshold": self.burn_threshold,
               "min_events": self.min_events}
        if self.latency_budget_ms is not None:
            out["latency_budget_ms"] = self.latency_budget_ms
        if self.histogram is not None:
            out["histogram"] = self.histogram
        if self.kind == "counter_ratio":
            out["bad_metrics"] = self.bad_metrics
            out["total_metrics"] = self.total_metrics
        return out


def default_slos(lane: str = "interactive",
                 latency_budget_ms: float = 2000.0,
                 fast_window_s: float = 5.0,
                 slow_window_s: float = 30.0) -> List[SLO]:
    """The standing objective set the benches arm: one of each kind for
    the given lane, windows scaled to bench runs (production deployments
    declare hours-scale windows; the math is identical)."""
    return [
        SLO(f"{lane}-latency-p99", "latency", target=0.99,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane=lane, latency_budget_ms=latency_budget_ms),
        SLO(f"{lane}-errors", "error_rate", target=0.999,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane=lane),
        SLO(f"{lane}-availability", "availability", target=0.999,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane=lane),
        SLO(f"{lane}-rejections", "rejection_rate", target=0.95,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane=lane),
    ]


def ingest_slos(refresh_budget_ms: float = 1000.0,
                backlog_budget_segments: float = 8.0,
                fast_window_s: float = 5.0,
                slow_window_s: float = 30.0) -> List[SLO]:
    """The write-path objective pair the ingest observatory arms.

    Both ride the latency machinery over explicit histograms rather than
    a search lane:

    - refresh-lag: fraction of refresh-to-visible samples within
      `refresh_budget_ms` must stay >= target. A stalled or throttled
      refresh pushes accept->searchable deltas over budget and burns.
    - merge-backlog burn: the backlog-depth sketch (sampled each refresh)
      treated as a "latency" whose budget is a segment count. Sustained
      backlog above `backlog_budget_segments` burns error budget — the
      signal a defer-merges actuator would consume.
    """
    return [
        SLO("ingest-refresh-lag", "latency", target=0.95,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane="ingest", latency_budget_ms=refresh_budget_ms,
            histogram="indexing.refresh_to_visible_ms"),
        SLO("ingest-merge-backlog", "latency", target=0.90,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            lane="ingest", latency_budget_ms=backlog_budget_segments,
            histogram="indexing.merge.backlog_depth"),
    ]


class SLOEngine:
    """Holds armed objectives, evaluates them per sampler tick, owns the
    alert state machine. Disarmed (the default) it is inert: zero armed
    SLOs means `evaluate()` returns immediately and no listener rides
    the sampler — clean-run responses and timings stay untouched."""

    def __init__(self, sampler: Optional[TimeSeriesSampler] = None,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None):
        self.sampler = sampler if sampler is not None else SAMPLER
        self.registry = registry if registry is not None else METRICS
        self._recorder = recorder         # None -> module RECORDER, lazily
        self._lock = threading.Lock()
        self._slos: "OrderedDict[str, SLO]" = OrderedDict()
        self._status: Dict[str, dict] = {}
        self._alerts: deque = deque(maxlen=64)
        self.alerts_fired = 0
        self.refire_cooldown_s = 30.0
        # alert subscribers (serving/remediator.py closes the loop from
        # detection to ACTION here): invoked OUTSIDE the engine lock with
        # the alert dict, exception-isolated — a listener fault can never
        # break firing or deadlock evaluation
        self._alert_listeners: List = []

    # ---------------- arm / disarm ----------------

    @property
    def armed(self) -> bool:
        return bool(self._slos)

    def arm(self, slos: Sequence[SLO], start_sampler: bool = False) -> None:
        """Register objectives and hook evaluation onto the sampler's
        tick. Idempotent per SLO name (latest wins)."""
        with self._lock:
            for s in slos:
                self._slos[s.name] = s
                self._status.setdefault(s.name, {
                    "state": "ok", "since_mono": time.monotonic()})
                for h in s.tracked_histograms():
                    self.sampler.track_histogram(h)
        self.sampler.add_listener(self._on_sample)
        if start_sampler:
            self.sampler.ensure_started()

    def disarm(self) -> None:
        self.sampler.remove_listener(self._on_sample)
        with self._lock:
            self._slos.clear()
            self._status.clear()
            self._alerts.clear()

    def add_alert_listener(self, fn) -> None:
        """Subscribe to firing alerts (idempotent). `fn(alert_dict)` runs
        after every rising-edge fire, outside the engine lock."""
        with self._lock:
            if fn not in self._alert_listeners:
                self._alert_listeners.append(fn)

    def remove_alert_listener(self, fn) -> None:
        with self._lock:
            if fn in self._alert_listeners:
                self._alert_listeners.remove(fn)

    def _on_sample(self, _sampler) -> None:
        self.evaluate()

    # ---------------- evaluation ----------------

    def evaluate(self) -> Dict[str, dict]:
        """One pass over every armed SLO; returns the status map. Called
        per sampler tick (listener) or directly by tests/surfaces."""
        with self._lock:
            slos = list(self._slos.values())
        out: Dict[str, dict] = {}
        fired: List[dict] = []
        for s in slos:
            fast = s.burn(self.sampler, s.fast_window_s)
            slow = s.burn(self.sampler, s.slow_window_s)
            firing = (fast["burn_rate"] > s.burn_threshold
                      and slow["burn_rate"] > s.burn_threshold
                      and fast["total"] + slow["total"] >= s.min_events)
            g = self.registry.gauge
            g(f"slo.{s.name}.burn_fast").set(fast["burn_rate"])
            g(f"slo.{s.name}.burn_slow").set(slow["burn_rate"])
            g(f"slo.{s.name}.firing").set(1.0 if firing else 0.0)
            now = time.monotonic()
            with self._lock:
                st = self._status.setdefault(
                    s.name, {"state": "ok", "since_mono": now})
                was = st["state"]
                st["fast"] = fast
                st["slow"] = slow
                st["evaluated_mono"] = round(now, 6)
                if firing and was != "firing":
                    st["state"] = "firing"
                    st["since_mono"] = now
                    # the cooldown rate-limits alerts to one per window;
                    # the stamp moves ONLY when an alert actually fires —
                    # stamping suppressed edges would let a fast flapper
                    # silence itself forever
                    refire_ok = (now - st.get("last_fired_mono", -1e18)
                                 >= self.refire_cooldown_s)
                    if refire_ok:
                        st["last_fired_mono"] = now
                        self.alerts_fired += 1
                        self.registry.counter("slo.alerts_total").inc()
                        fired.append(self._fire_locked(s, fast, slow,
                                                       now))
                elif not firing and was == "firing":
                    st["state"] = "ok"
                    st["since_mono"] = now
                out[s.name] = dict(st)
        if fired:
            with self._lock:
                listeners = list(self._alert_listeners)
            for alert in fired:
                for fn in listeners:
                    try:
                        fn(dict(alert))
                    except Exception:   # noqa: BLE001 — a remediation
                        # listener fault must never break detection
                        pass
        return out

    # ---------------- firing ----------------

    def _fire_locked(self, s: SLO, fast: dict, slow: dict,
                     now: float) -> dict:
        """Rising-edge actions (called under self._lock): alert-log
        entry, `slo.burn` recorder event carrying the offending window's
        series AND the top query fingerprints active in that window
        (obs/insights.py — the blame half of detection: WHAT burned the
        budget, not just that it burned), and a frozen dump bundle.
        Each fingerprint entry links its worst flight-recorder timeline,
        so the dump is one hop from a full request journal. Returns the
        alert dict for the (post-lock) listener fan-out."""
        series = {m: self._bounded_series(m, s.slow_window_s)
                  for m in s.series_metrics()}
        top_fps = self._insights_top(s.slow_window_s)
        alert = {"slo": s.name, "slo_kind": s.kind, "lane": s.lane,
                 "at_mono": round(now, 6),
                 "fast": fast, "slow": slow,
                 "burn_threshold": s.burn_threshold,
                 "top_fingerprints": top_fps}
        self._alerts.append(dict(alert, series_metrics=sorted(series)))
        rec = self._rec()
        if rec is not None and rec.enabled:
            tl = rec.start("slo", slo=s.name, slo_kind=s.kind,
                           lane=s.lane)
            if tl:
                rec.record(tl, "slo.burn", **dict(alert, series=series))
                rec.trigger(
                    "slo_burn", [tl],
                    note=f"SLO [{s.name}] burn fast="
                         f"{fast['burn_rate']}x slow={slow['burn_rate']}x "
                         f"(threshold {s.burn_threshold}x)")
        return alert

    @staticmethod
    def _insights_top(window_s: float) -> list:
        """Top query fingerprints active in the offending window —
        bounded, label-safe (hashes + numbers + value-free shapes).
        Forensics must never break firing: any insights fault reads as
        an empty attribution list."""
        try:
            from .insights import INSIGHTS
            return INSIGHTS.top_fingerprints(window_s,
                                             n=_ALERT_TOP_FINGERPRINTS)
        except Exception:       # noqa: BLE001 — attribution is advisory
            return []

    def _bounded_series(self, metric: str, window_s: float) -> dict:
        h = self.sampler.history(metric, window_s)
        pts = h["points"]
        if len(pts) > _ALERT_SERIES_POINTS:
            h["points"] = pts[-_ALERT_SERIES_POINTS:]
            h["truncated"] = True
        return h

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from .flight_recorder import RECORDER
        return RECORDER

    # ---------------- surfaces ----------------

    def status(self) -> dict:
        """`GET /_slo` payload: definitions + live burn/state + the
        recent alert log."""
        with self._lock:
            slos = [s.describe() for s in self._slos.values()]
            status = {n: dict(st) for n, st in self._status.items()}
            alerts = list(self._alerts)
        return {"armed": bool(slos), "slos": slos, "status": status,
                "alerts": alerts, "alerts_fired": self.alerts_fired}

    def stats(self) -> dict:
        """`_nodes/stats` "slo" block (compact: no alert log)."""
        with self._lock:
            states = {n: st.get("state", "ok")
                      for n, st in self._status.items()}
            burns = {n: {"fast": (st.get("fast") or {}).get("burn_rate"),
                         "slow": (st.get("slow") or {}).get("burn_rate")}
                     for n, st in self._status.items()}
        return {"armed": self.armed, "objectives": len(states),
                "alerts_fired": self.alerts_fired,
                "states": states, "burn_rates": burns}


# process-default engine over the process-default sampler
SLO_ENGINE = SLOEngine()
