"""Flight recorder: a per-request black-box event journal.

PR 3's telemetry answers "how fast is the system on average"; this module
answers "what exactly happened to THAT request". Every search gets a
*timeline* — an ordered sequence of structured events from REST accept
through wlm lane classification, scheduler enqueue/flush (with batch
peers), launch (mesh vs fastpath, dispatch-lock wait, new program
compiles), fetch, fastpath ladder rungs, and every degradation
(deadline miss, completion wedge, cancel, 429, direct fallback) — so a
single bad request under serving load is reconstructable after the fact.
Reference analog: the forensic half of OpenSearch's `_tasks` +
`_nodes/hot_threads` introspection, with the event-journal discipline of
an aircraft flight recorder: always on, fixed cost, frozen on anomaly.

Design constraints (the hot path is the serving scheduler's dispatcher
and the fastpath ladder):

- **Lock-light ring.** `record()` is one atomic sequence bump
  (`itertools.count` — a C-level single-op under the GIL) plus one slot
  store of a fully-built tuple. No lock, no allocation beyond the event
  tuple itself; concurrent writers can interleave but never tear a slot
  (readers see either the old tuple or the new one) and never lose an
  event while the ring is within capacity (each sequence number owns a
  distinct slot until wraparound).
- **Lazy payloads.** Emission sites in serving/search hot paths guard
  with `if RECORDER.enabled:` BEFORE building the event's field dict —
  the disabled path is one attribute read. oslint OSL505 enforces the
  guard (and the monotonic-timestamp discipline) statically.
- **Monotonic time.** Events carry `time.monotonic()` only; dumps
  convert to wall clock through a single (wall, mono) anchor captured at
  construction, so a stepped wall clock can reorder nothing.

Timelines are keyed to the existing trace context: `Node.search` stamps
the root span id onto the timeline, and `cluster/distnode.py` carries
`(node, timeline)` on its `/_internal` RPCs so the remote side's events
come back on the response and graft into the coordinator's timeline —
one stitched cross-node story per distributed search.

On an anomaly trigger — deadline miss, completion wedge, scheduler
rejection burst, oracle mismatch, slowlog threshold, or a manual
`POST /_flight_recorder/dump` — the recorder freezes the relevant
timelines into a JSON dump bundle (bounded count, bounded timelines per
bundle) retrievable via `GET /_flight_recorder`.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence

from ..utils.metrics import METRICS

__all__ = ["FlightRecorder", "RECORDER", "current", "set_current",
           "reset_current"]

# ambient timeline id for the executing request (0 = none). Propagates
# into pool workers via the context-carrying submit in utils/threadpool;
# the serving scheduler's own threads carry ids explicitly on entries.
_current_tl: contextvars.ContextVar = contextvars.ContextVar(
    "opensearch_tpu_timeline", default=0)


def current() -> int:
    return _current_tl.get()


def set_current(tl: int):
    return _current_tl.set(tl)


def reset_current(token) -> None:
    _current_tl.reset(token)


def _truthy_env(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("", "0", "false", "no")


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


class FlightRecorder:
    """Fixed-size event ring + bounded timeline registry + dump store.

    One per process (module singleton `RECORDER`), like `utils/trace.py`
    TRACER and `utils/metrics.py` METRICS — one node per process is the
    deployment reality, and multi-node tests sharing a process simply
    share the black box (events carry the node via timeline meta)."""

    # anomaly reasons with a cooldown (storm-shaped triggers must not
    # flood the dump store); wedges/deadline misses always dump
    _COOLDOWN_REASONS = ("rejection_burst", "slowlog", "oracle_mismatch",
                         "retry_storm", "slo_burn", "refresh_stall")

    def __init__(self, capacity: Optional[int] = None,
                 max_dumps: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 max_dump_timelines: int = 32,
                 max_timeline_events: int = 512,
                 cooldown_s: float = 0.25,
                 burst_n: int = 8, burst_window_s: float = 1.0):
        env = os.environ
        self.capacity = int(capacity if capacity is not None
                            else env.get("OPENSEARCH_TPU_FR_CAPACITY", 4096))
        if self.capacity < 16:
            raise ValueError("flight recorder capacity must be >= 16")
        self.max_dumps = int(max_dumps if max_dumps is not None
                             else env.get("OPENSEARCH_TPU_FR_MAX_DUMPS", 16))
        if enabled is None:
            enabled = _truthy_env("OPENSEARCH_TPU_FLIGHT_RECORDER", True)
        self.enabled = bool(enabled)
        self.max_dump_timelines = int(max_dump_timelines)
        self.max_timeline_events = int(max_timeline_events)
        self.cooldown_s = float(cooldown_s)
        self.burst_n = int(burst_n)
        self.burst_window_s = float(burst_window_s)
        # wall-clock anchor: events carry monotonic time only; dumps
        # convert through this single pair (plain timestamp, never
        # differenced against monotonic readings from another clock)
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()
        # the ring: slot i%capacity holds (seq, tl, t_mono, kind, fields)
        self._slots: List[Optional[tuple]] = [None] * self.capacity
        self._seq = itertools.count()
        # timeline ids + bounded metadata (allocation is once per request
        # — a small lock here is fine; only record() must stay lock-free)
        self._tl_ids = itertools.count(1)
        self._timelines: "OrderedDict[int, dict]" = OrderedDict()
        self._meta_lock = threading.Lock()
        self._meta_cap = max(self.capacity // 4, 256)
        # dump store + trigger bookkeeping
        self._dump_lock = threading.Lock()
        self._dumps: deque = deque(maxlen=self.max_dumps)
        self._dump_ids = itertools.count(1)
        self._last_trigger: Dict[str, float] = {}
        self.trigger_counts: Dict[str, int] = {}
        self.suppressed_triggers = 0
        self.timelines_started = 0
        # 429-burst detection window: (mono, tl) of recent rejections.
        # Own lock (NOT _dump_lock: trigger() takes that) — concurrent
        # rejecting schedulers must not race the window scan
        self._rej_lock = threading.Lock()
        self._rejections: deque = deque(maxlen=max(self.burst_n * 4, 64))

    # ---------------- timeline lifecycle ----------------

    def start(self, kind: str, **meta) -> int:
        """Allocate a timeline; returns its id (0 when disabled — every
        downstream record() on id 0 is a no-op)."""
        if not self.enabled:
            return 0
        tl = next(self._tl_ids)
        m = {"kind": kind, "t_mono": time.monotonic()}
        if meta:
            m.update(meta)
        with self._meta_lock:
            self.timelines_started += 1
            self._timelines[tl] = m
            while len(self._timelines) > self._meta_cap:
                self._timelines.popitem(last=False)
        return tl

    def annotate(self, tl: int, **meta) -> None:
        """Attach metadata to a live timeline (e.g. the trace root span
        id, once known)."""
        if not self.enabled or not tl:
            return
        with self._meta_lock:
            m = self._timelines.get(tl)
            if m is not None:
                m.update(meta)

    # ---------------- the hot path ----------------

    def record(self, tl: int, kind: str, **fields) -> None:
        """Append one event. Near-free: one counter bump + one slot
        store. Callers on hot paths must guard `if RECORDER.enabled:`
        before building `fields` (oslint OSL505)."""
        if not self.enabled or not tl:
            return
        i = next(self._seq)
        self._slots[i % self.capacity] = (
            i, tl, time.monotonic(), kind, fields or None)

    def graft(self, tl: int, events: Optional[Sequence[dict]],
              node: str) -> None:
        """Stitch a remote node's serialized timeline events (carried on
        a distnode RPC response) into local timeline `tl` — the event
        analog of `Tracer.attach_remote`. Remote monotonic stamps are
        meaningless here, so they ride as `remote_t_mono` and the event
        takes a local receive-time stamp (ordering within the remote leg
        is preserved by `remote_seq`)."""
        if not self.enabled or not tl or not events:
            return
        for ev in events:
            if not isinstance(ev, dict):
                continue
            fields = {k: v for k, v in ev.items()
                      if k not in ("seq", "t_mono", "kind")}
            fields["node"] = node
            fields["remote_seq"] = ev.get("seq")
            fields["remote_t_mono"] = ev.get("t_mono")
            self.record(tl, str(ev.get("kind", "remote")), **fields)

    # ---------------- reads (cold paths) ----------------

    def _scan(self) -> List[tuple]:
        """Snapshot the ring's valid events in sequence order. Writers
        may race the scan; a slot read is atomic (one tuple ref), so the
        result is a consistent set of whole events."""
        out = [s for s in self._slots if s is not None]
        out.sort(key=lambda s: s[0])
        return out

    def timeline_events(self, tl: int,
                        events: Optional[List[tuple]] = None) -> List[dict]:
        """Serialized events for one timeline, oldest first (bounded by
        max_timeline_events, keeping the newest). Runs per distnode RPC
        leg, so without a pre-scanned `events` list it filters to the
        timeline BEFORE sorting — cost proportional to the timeline's
        own event count, not capacity·log(capacity)."""
        if events is not None:
            evs = [s for s in events if s[1] == tl]
        else:
            evs = [s for s in self._slots
                   if s is not None and s[1] == tl]
            evs.sort(key=lambda s: s[0])
        evs = evs[-self.max_timeline_events:]
        return [{"seq": s[0], "t_mono": round(s[2], 6), "kind": s[3],
                 **({k: _jsonable(v) for k, v in s[4].items()}
                    if s[4] else {})}
                for s in evs]

    def timeline_meta(self, tl: int) -> Optional[dict]:
        with self._meta_lock:
            m = self._timelines.get(tl)
            return dict(m) if m is not None else None

    def _wall(self, t_mono: float) -> float:
        return self._anchor_wall + (t_mono - self._anchor_mono)

    # ---------------- anomaly dumps ----------------

    def trigger(self, reason: str, tl_ids: Optional[Sequence[int]] = None,
                note: Optional[str] = None,
                force: bool = False) -> Optional[dict]:
        """Freeze the given timelines (None = the most recent ones in
        the ring) into a dump bundle. Storm-shaped reasons are
        rate-limited by `cooldown_s`; wedge/deadline-miss style reasons
        (and force=True) always dump."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._dump_lock:
            self.trigger_counts[reason] = \
                self.trigger_counts.get(reason, 0) + 1
            if not force and reason in self._COOLDOWN_REASONS:
                last = self._last_trigger.get(reason)
                if last is not None and now - last < self.cooldown_s:
                    self.suppressed_triggers += 1
                    return None
            self._last_trigger[reason] = now
            bundle = self._build_bundle(reason, tl_ids, note, now)
            self._dumps.append(bundle)
        METRICS.counter("flight_recorder.dumps").inc()
        METRICS.counter(f"flight_recorder.dump.{reason}").inc()
        return bundle

    def _build_bundle(self, reason: str, tl_ids, note, now: float) -> dict:
        events = self._scan()
        if tl_ids:
            want = list(dict.fromkeys(int(t) for t in tl_ids if t))
        else:
            # manual snapshot: every timeline present in the ring,
            # newest first
            seen: "OrderedDict[int, None]" = OrderedDict()
            for s in reversed(events):
                seen.setdefault(s[1], None)
            want = list(seen)
        want = want[: self.max_dump_timelines]
        timelines = {}
        for tl in want:
            evs = self.timeline_events(tl, events)
            for ev in evs:
                ev["t_wall"] = round(self._wall(ev["t_mono"]), 6)
            timelines[str(tl)] = {"meta": _jsonable(self.timeline_meta(tl)),
                                  "events": evs}
        return {"id": next(self._dump_ids), "reason": reason,
                **({"note": note} if note else {}),
                "at_mono": round(now, 6),
                "at_wall": round(self._wall(now), 6),
                "timelines": timelines,
                "timeline_count": len(timelines)}

    def note_rejection(self, tl: int = 0) -> None:
        """Count one scheduler 429; when `burst_n` land inside
        `burst_window_s`, freeze the rejected timelines (a rejection
        storm is an anomaly even though each 429 alone is policy)."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._rej_lock:
            self._rejections.append((now, tl))
            recent = [(t, x) for (t, x) in self._rejections
                      if now - t <= self.burst_window_s]
        if len(recent) >= self.burst_n:
            self.trigger("rejection_burst",
                         [x for _, x in recent if x],
                         note=f"{len(recent)} scheduler rejections in "
                              f"{self.burst_window_s}s")

    def note_lock_inversion(self, first: str, second: str,
                            stack_now: str, stack_prior: str) -> None:
        """Freeze a dump when the runtime lock witness
        (devtools/lockwitness.py) observes an acquisition-order
        inversion — both stacks ride in the bundle so the two
        conflicting code paths are named even after the process moves
        on. Always dumps (force=True): a witnessed inversion is a
        latent deadlock, never storm noise."""
        if not self.enabled:
            return
        tl = self.start("lock_inversion", first=first, second=second)
        self.record(tl, "lock_inversion", first=first, second=second,
                    stack_now=stack_now, stack_prior=stack_prior)
        self.trigger("lock_inversion", [tl],
                     note=f"{second} acquired while holding {first} "
                          "after the opposite order was witnessed",
                     force=True)

    def dumps(self, limit: Optional[int] = None) -> List[dict]:
        with self._dump_lock:
            out = list(self._dumps)
        if limit is not None:
            out = out[-limit:]
        return list(reversed(out))

    # ---------------- stats + test hooks ----------------

    def stats(self) -> dict:
        events = self._scan()
        total = (events[-1][0] + 1) if events else 0
        with self._dump_lock:
            dump_meta = [{"id": d["id"], "reason": d["reason"],
                          "at_wall": d["at_wall"],
                          "timeline_count": d["timeline_count"]}
                         for d in reversed(self._dumps)]
            triggers = dict(self.trigger_counts)
            suppressed = self.suppressed_triggers
        return {"enabled": self.enabled,
                "capacity": self.capacity,
                "events": total,
                "retained_events": len(events),
                "overwritten_events": max(total - self.capacity, 0),
                "timelines_started": self.timelines_started,
                "dumps": dump_meta,
                "triggers": triggers,
                "suppressed_triggers": suppressed}

    def reset(self) -> None:
        """Drop every event, timeline and dump — isolation hook for
        tests and bench cells (mirrors MetricsRegistry.reset)."""
        self._slots = [None] * self.capacity
        self._seq = itertools.count()
        with self._meta_lock:
            self._timelines.clear()
            self.timelines_started = 0
        with self._dump_lock:
            self._dumps.clear()
            self._last_trigger.clear()
            self.trigger_counts.clear()
            self.suppressed_triggers = 0
        with self._rej_lock:
            self._rejections.clear()


# process-default recorder (one node per process, like TRACER/METRICS)
RECORDER = FlightRecorder()
