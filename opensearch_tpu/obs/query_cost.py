"""Per-query device cost accounting: predicted vs. actual bytes moved.

The HBM ledger (`obs/hbm_ledger.py`) answers "what is resident"; this
module answers "what does one query MOVE". Two curves drive device sparse
retrieval engineering (GPUSparse, PAPERS.md arxiv 2606.26441): resident
footprint vs. bytes gathered per query — and ROADMAP item 1
(impact-quantized postings) claims to shrink the second. This module
commits the baseline that claim will be measured against.

Model (documented in docs/OBSERVABILITY.md):

- **Predicted, at plan time, from CSR block stats only.** For each scoring
  term group the query touches in a segment, every term row contributes
  its true posting count `df`; a codec-v1 posting slot is 8 bytes
  (doc_id i32 + tf/packed-tfdl f32/i32), a codec-v2 eager slot is
  `4 + bits/8` bytes (doc_id i32 + u8/u16 quantized impact — the
  executor's `_cost_predicted` consults the segment codec per field).
  `predicted_bytes_gathered = Σ df × slot`, `predicted_scatter_adds =
  Σ df`, `predicted_topk_work = window` per planned segment.
- **Actual, from launched program shapes.** The programs gather PADDED
  shapes: the XLA path flattens a term group into a pow2 `bucket`
  (`ops.pick_bucket`), so it moves `bucket × 8` bytes and scatter-adds
  `bucket` slots; the codec-v2 impact pass (search/impactpath.py, path
  "impact") moves `bucket × (4 + bits/8)` bytes over its block-pruned
  windows; the fastpath kernel DMAs per-term lane-aligned windows
  (`nrows × LANES` slots of 8 bytes) and extracts `K` top-k lanes per
  kernel row. The predicted/actual gap is therefore exactly the padding +
  alignment tax.

An accumulator rides a contextvar for the duration of one
`executor.search_shards` call (the host shard loop + fastpath ladder; the
mesh SPMD path and cross-request coalesced batches execute on other
threads and are attributed to their own launch counters instead). At
finish it records DDSketch histograms (`cost.bytes_per_query`,
`cost.predicted_bytes_per_query`, `cost.predicted_vs_actual_pct`) served
by `_nodes/stats` and `/_metrics`, and the snapshot surfaces as the
`cost` block of a `profile` response and the `explain=device_plan` view.

`OPENSEARCH_TPU_COST=0` disables accounting entirely (the
`measure_concurrency.py` gate pins cost-on qps >= 0.98x cost-off with
byte-identical responses).
"""

from __future__ import annotations

import contextvars
import os
import threading
from typing import List, Optional, Tuple

from ..utils.metrics import METRICS

__all__ = ["QueryCost", "current", "start", "finish", "enabled",
           "POSTING_SLOT_BYTES", "spec_gather_shape"]

# bytes moved per posting slot: doc_id i32 + (tf f32 | packed tf·dl i32)
POSTING_SLOT_BYTES = 8

_current: contextvars.ContextVar = contextvars.ContextVar(
    "opensearch_tpu_query_cost", default=None)


def enabled() -> bool:
    return os.environ.get("OPENSEARCH_TPU_COST", "") not in (
        "0", "false", "no")


class QueryCost:
    """Accumulates one search's predicted and actual device work.

    Thread-safe: the fastpath ladder's escalation rungs and pool-executed
    segment work may note from worker threads carrying the contextvar."""

    __slots__ = ("detail", "predicted_bytes", "predicted_scatter",
                 "predicted_topk", "actual_bytes", "actual_scatter",
                 "actual_topk", "launches", "segments", "_lock")

    def __init__(self, detail: bool = False) -> None:
        self.detail = detail
        self.predicted_bytes = 0
        self.predicted_scatter = 0
        self.predicted_topk = 0
        self.actual_bytes = 0
        self.actual_scatter = 0
        self.actual_topk = 0
        self.launches = 0
        # per-segment plan entries (explain=device_plan only)
        self.segments: List[dict] = []
        self._lock = threading.Lock()

    def note_predicted(self, bytes_: int, scatter: int, topk: int,
                       segment=None) -> None:
        with self._lock:
            self.predicted_bytes += int(bytes_)
            self.predicted_scatter += int(scatter)
            self.predicted_topk += int(topk)
            if self.detail and segment is not None:
                self.segments.append(  # oslint: disable=OSL602 -- per-request accumulator: dies at finish(), bounded by the request's own plan size, never workload cardinality
                    {"segment": getattr(segment, "name", str(segment)),
                     "predicted_bytes_gathered": int(bytes_),
                     "predicted_scatter_adds": int(scatter),
                     "predicted_topk_work": int(topk)})

    def note_actual(self, bytes_: int, scatter: int, topk: int,
                    launches: int = 1, path: str = "",
                    segment=None) -> None:
        with self._lock:
            self.actual_bytes += int(bytes_)
            self.actual_scatter += int(scatter)
            self.actual_topk += int(topk)
            self.launches += int(launches)
            if self.detail:
                self.segments.append(  # oslint: disable=OSL602 -- per-request accumulator: dies at finish(), bounded by the request's own plan size, never workload cardinality
                    {"segment": (getattr(segment, "name", str(segment))
                                 if segment is not None else None),
                     "path": path,
                     "actual_bytes_gathered": int(bytes_),
                     "actual_scatter_adds": int(scatter),
                     "actual_topk_work": int(topk),
                     "launches": int(launches)})

    @property
    def active(self) -> bool:
        return bool(self.launches or self.predicted_bytes
                    or self.actual_bytes)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "predicted_bytes_gathered": self.predicted_bytes,
                "predicted_scatter_adds": self.predicted_scatter,
                "predicted_topk_work": self.predicted_topk,
                "actual_bytes_gathered": self.actual_bytes,
                "actual_scatter_adds": self.actual_scatter,
                "actual_topk_work": self.actual_topk,
                "launches": self.launches,
            }
            if self.actual_bytes and self.predicted_bytes:
                out["predicted_vs_actual_pct"] = round(
                    100.0 * self.predicted_bytes / self.actual_bytes, 2)
            return out


def current() -> Optional[QueryCost]:
    return _current.get()


def start(detail: bool = False) -> tuple:
    """Install a fresh accumulator; returns (accumulator, token) for the
    paired `finish`."""
    qc = QueryCost(detail=detail)
    return qc, _current.set(qc)


def finish(token, record: bool = True) -> None:
    """Uninstall and (when the query did device work) record the
    per-query histograms."""
    qc = _current.get()
    _current.reset(token)
    if qc is None or not record or not qc.active:
        return
    if METRICS.enabled:
        # DDSketch histograms: values are BYTES (the registry's log bins
        # are value-agnostic; the *_ms key names in snapshots read as
        # raw-unit values for these series)
        if qc.actual_bytes:
            METRICS.histogram("cost.bytes_per_query").record(
                float(qc.actual_bytes))
        if qc.predicted_bytes:
            METRICS.histogram("cost.predicted_bytes_per_query").record(
                float(qc.predicted_bytes))
        if qc.actual_bytes and qc.predicted_bytes:
            METRICS.histogram("cost.predicted_vs_actual_pct").record(
                100.0 * qc.predicted_bytes / qc.actual_bytes)


def bytes_per_query_stamp() -> dict:
    """The BENCH-json `extra.bytes_per_query` stamp: count/p50/p95 of the
    predicted and actual bytes-gathered histograms plus the
    reconciliation percentiles. One definition for bench.py,
    scripts/measure_concurrency.py and scripts/hbm_report.py — the
    DDSketch snapshot's `*_ms` keys carry raw BYTE values for these
    series (the registry's log bins are unit-agnostic)."""
    hists = METRICS.snapshot()["histograms"]

    def _pct(name: str) -> dict:
        h = hists.get(name) or {}
        return {"count": h.get("count", 0), "p50": h.get("p50_ms"),
                "p95": h.get("p95_ms")}

    return {"actual": _pct("cost.bytes_per_query"),
            "predicted": _pct("cost.predicted_bytes_per_query"),
            "predicted_vs_actual_pct": _pct("cost.predicted_vs_actual_pct")}


# ---------------------------------------------------------------------
# launched-shape walkers
# ---------------------------------------------------------------------

# (spec kind, index of the pow2 gather bucket in the spec tuple): the
# compiler spec tuples whose programs flatten postings through
# `ops.gather_postings` — the launched gather width is the bucket
_BUCKET_SPECS = {"terms": 4, "xterms": 4, "sparse_dot": 4,
                 "rank_feature_post": 3}


def spec_gather_shape(spec) -> Tuple[int, int]:
    """-> (bytes_gathered, scatter_adds) of one prepared query spec tree,
    from the pow2 buckets its launched program will actually move.
    Aggregation specs reuse some kind names ("terms", "range") with
    string prefixes in slot 1 — query specs carry an int nid there, which
    is the discriminator."""
    bytes_ = 0
    slots = 0
    stack = [spec]
    while stack:
        node = stack.pop()
        if not isinstance(node, (tuple, list)):
            continue
        if node and isinstance(node[0], str) and len(node) > 1 \
                and isinstance(node[1], int):
            kind = node[0]
            bi = _BUCKET_SPECS.get(kind)
            if bi is not None and len(node) > bi \
                    and isinstance(node[bi], int):
                bytes_ += node[bi] * POSTING_SLOT_BYTES
                slots += node[bi]
            elif kind == "phrase" and len(node) > 4 \
                    and isinstance(node[4], tuple):
                # phrase pair arrays: (doc i32, pos i32) per slot
                for b in node[4]:
                    if isinstance(b, int):
                        bytes_ += b * POSTING_SLOT_BYTES
                        slots += b
        stack.extend(node)
    return bytes_, slots
