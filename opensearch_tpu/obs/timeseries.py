"""Time-series retention: a bounded ring of periodic registry snapshots.

PR 3's registry answers "what are the totals NOW"; this module answers
"what happened over the last N seconds" — the missing dimension for
debugging a chaos or traffic run: qps, queue depth, batch size, block
skip rate, HBM residency, and retry/failover counters become queryable
*series* (`GET /_nodes/stats/history?metric=...&window=...`) instead of
two hand-polled endpoint reads diffed in a notebook. Reference analog:
the OpenSearch Performance Analyzer's on-node metric store (fixed
retention, pull-based), scaled to this engine's one-process reality.

Sampler discipline (oslint OSL509 encodes all three statically):

- **Monotonic clock only.** Sample timestamps come from
  `time.monotonic()`; an NTP step must never reorder a series or produce
  a negative rate. Wall-clock display conversion goes through one
  (wall, mono) anchor captured at construction, the flight-recorder
  pattern.
- **Bounded ring.** Samples land in a `deque(maxlen=capacity)` — a
  sampler that `list.append`s forever is a slow memory leak wearing an
  observability costume.
- **Fixed per-tick cost.** A tick snapshots counter/gauge values (plain
  dict copies) and histogram (count, sum) pairs for every instrument,
  but full BIN maps only for explicitly tracked histograms (the SLO
  engine registers the ones its objectives window over) — the tick cost
  must not grow with how many latency sketches the process ever touched.

Threading: one daemon thread per sampler, parked on an `Event.wait`
(stoppable, not sleep-polling). The process singleton `SAMPLER` mirrors
METRICS/RECORDER/LEDGER — one node per process is the deployment
reality; co-resident test nodes share the ring exactly like they share
`/_metrics`. The thread does NOT auto-start: tests drive `sample_once()`
deterministically, servers and benches call `ensure_started()` (or set
`OPENSEARCH_TPU_TS=1`, which `cluster/node.py` honors at Node init).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..utils.metrics import METRICS, MetricsRegistry, sketch_percentile

__all__ = ["TimeSeriesSampler", "SAMPLER"]


class _Sample:
    """One tick: monotonic stamp + counter/gauge values + histogram
    (count, sum) pairs + full bins for tracked histograms."""

    __slots__ = ("t_mono", "counters", "gauges", "hists", "bins")

    def __init__(self, t_mono: float, counters: Dict[str, float],
                 gauges: Dict[str, float],
                 hists: Dict[str, tuple],
                 bins: Dict[str, Dict[int, int]]):
        self.t_mono = t_mono
        self.counters = counters
        self.gauges = gauges
        self.hists = hists
        self.bins = bins


class TimeSeriesSampler:
    """Bounded-ring periodic snapshots of a MetricsRegistry with
    delta/rate derivation on read."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None,
                 capacity: Optional[int] = None):
        env = os.environ
        self.registry = registry if registry is not None else METRICS
        self.interval_s = float(
            interval_s if interval_s is not None
            else env.get("OPENSEARCH_TPU_TS_INTERVAL_S", 1.0))
        if self.interval_s <= 0:
            raise ValueError("sampler interval must be > 0")
        self.capacity = int(capacity if capacity is not None
                            else env.get("OPENSEARCH_TPU_TS_CAPACITY", 512))
        if self.capacity < 2:
            raise ValueError("sampler capacity must be >= 2 (rates need "
                             "two points)")
        # the ring: bounded by construction (oslint OSL509)
        self._ring: deque = deque(maxlen=self.capacity)
        self._ring_lock = threading.Lock()
        self._track: set = set()          # histogram names sampled w/ bins
        self._listeners: List[Callable[["TimeSeriesSampler"], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self.ticks = 0
        # wall display anchor (single pair; samples carry monotonic only)
        self._anchor_wall = time.time()
        self._anchor_mono = time.monotonic()

    # ---------------- configuration ----------------

    def track_histogram(self, *names: str) -> None:
        """Sample full bin maps for these histograms, enabling windowed
        percentiles (`window_percentile`). The SLO engine registers the
        histograms its latency objectives read."""
        self._track.update(names)

    def add_listener(self, fn: Callable[["TimeSeriesSampler"], None]
                     ) -> None:
        """Called after every tick with the sampler (the SLO engine's
        evaluation hook). Listeners run on the sampler thread; they must
        be quick and must not raise."""
        if fn not in self._listeners:
            self._listeners.append(fn)  # oslint: disable=OSL509 -- listener registry: one append per arm()/registration, never per tick

    def remove_listener(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    # ---------------- lifecycle ----------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def ensure_started(self) -> None:
        with self._state_lock:
            if self.running:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ostpu-ts-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._state_lock:
            t = self._thread
            self._thread = None
        self._stop.set()
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def reset(self) -> None:
        """Drop the ring — isolation hook for tests/bench cells
        (mirrors MetricsRegistry.reset). Tracking and listeners stay."""
        with self._ring_lock:
            self._ring.clear()
            self.ticks = 0

    def _run(self) -> None:
        # Event.wait is the stoppable park (not sleep-polling: the stop()
        # signal wakes it immediately); monotonic cadence
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:       # noqa: BLE001 — a sampler must never
                pass                # take the process down with it

    # ---------------- the tick ----------------

    def sample_once(self) -> None:
        """One snapshot into the ring + listener fan-out. Public so tests
        and the deadline-free single-node path can tick deterministically
        without the thread."""
        reg = self.registry
        with reg._lock:
            counters = {n: c.value for n, c in reg._counters.items()}
            gauges = {n: g.value for n, g in reg._gauges.items()}
            hitems = list(reg._hists.items())
        hists: Dict[str, tuple] = {}
        bins: Dict[str, Dict[int, int]] = {}
        for n, h in hitems:
            with h._lock:
                hists[n] = (h.count, h.sum_ms)
                if n in self._track:
                    bins[n] = dict(h._bins)
        s = _Sample(time.monotonic(), counters, gauges, hists, bins)
        with self._ring_lock:
            self._ring.append(s)
            self.ticks += 1
        for fn in list(self._listeners):
            try:
                fn(self)
            except Exception:       # noqa: BLE001 — a listener fault must
                # not kill the ring; counted, never silent (OSL508 spirit)
                reg.counter("timeseries.listener_errors").inc()

    # ---------------- reads ----------------

    def _window(self, window_s: float) -> List[_Sample]:
        with self._ring_lock:
            samples = list(self._ring)
        if not samples:
            return []
        cutoff = samples[-1].t_mono - float(window_s)
        # keep one sample BEFORE the cutoff when available: deltas over
        # the window need the entering value
        out = [s for s in samples if s.t_mono >= cutoff]
        older = [s for s in samples if s.t_mono < cutoff]
        if older:
            out = [older[-1]] + out
        return out

    @staticmethod
    def _metric_value(s: _Sample, metric: str):
        if metric in s.counters:
            return ("counter", s.counters[metric])
        if metric in s.gauges:
            return ("gauge", s.gauges[metric])
        if metric in s.hists:
            return ("histogram", s.hists[metric])
        return (None, None)

    def history(self, metric: str, window_s: float = 60.0) -> dict:
        """The `_nodes/stats/history` payload for one metric: raw points
        plus the derived per-interval rate for monotonic kinds (counters
        and histogram counts — qps is `search.lane.*.requests` under
        this derivation). Gauges report values only. Timestamps carry
        both the monotonic stamp (exact spacing) and an anchored wall
        stamp (display)."""
        samples = self._window(window_s)
        points = []
        prev = None
        kind_seen = None
        for s in samples:
            kind, v = self._metric_value(s, metric)
            if kind is None:
                prev = None
                continue
            kind_seen = kind
            if kind == "histogram":
                cnt, sm = v
                pt = {"t_mono": round(s.t_mono, 6),
                      "t_wall": round(self._wall(s.t_mono), 3),
                      "count": cnt, "sum_ms": round(sm, 3)}
                if prev is not None:
                    dt = s.t_mono - prev[0]
                    dc = cnt - prev[1][0]
                    if dt > 0:
                        pt["rate"] = round(dc / dt, 4)
                        dsum = sm - prev[1][1]
                        pt["mean_ms"] = (round(dsum / dc, 4) if dc > 0
                                         else None)
            else:
                pt = {"t_mono": round(s.t_mono, 6),
                      "t_wall": round(self._wall(s.t_mono), 3),
                      "value": v}
                if kind == "counter" and prev is not None:
                    dt = s.t_mono - prev[0]
                    if dt > 0:
                        pt["rate"] = round((v - prev[1]) / dt, 4)
            points.append(pt)
            prev = (s.t_mono, v)
        return {"metric": metric, "kind": kind_seen,
                "window_s": float(window_s),
                "interval_s": self.interval_s, "points": points}

    def counter_delta(self, metric: str, window_s: float) -> float:
        """Counter (or histogram-count) increase across the window —
        the SLO engine's bad/total event source. Instruments are
        create-on-first-use, so a metric ABSENT from a snapshot was
        definitionally 0 then — a counter born mid-window contributes
        its full value, not a silent 0 delta. Clamped at 0: a registry
        reset mid-window must not produce a negative burn."""
        samples = self._window(window_s)
        if len(samples) < 2:
            return 0.0
        vals = []
        for s in samples:
            kind, v = self._metric_value(s, metric)
            if kind == "histogram":
                vals.append(v[0])
            elif kind is not None:
                vals.append(v)
            else:
                vals.append(0.0)
        return max(float(vals[-1]) - float(vals[0]), 0.0)

    def window_hist_delta(self, name: str, window_s: float) -> dict:
        """The tracked histogram's bin delta across the window (wire
        shape) — windowed percentiles via `sketch_percentile`, and the
        above-threshold counting latency SLOs burn on. A histogram that
        did not EXIST at a tick reads as empty bins then (create-on-
        first-use); a tick where it existed but was untracked is
        unusable and skipped."""
        pts = []
        for s in self._window(window_s):
            if name in s.bins:
                pts.append((s.t_mono, s.bins[name]))
            elif name not in s.hists:
                pts.append((s.t_mono, {}))    # born later: zero baseline
        if len(pts) < 2:
            return {"bins": {}, "count": 0}
        first, last = pts[0][1], pts[-1][1]
        bins = {}
        for b, c in last.items():
            d = c - first.get(b, 0)
            if d > 0:
                bins[b] = d
        return {"bins": bins,
                "count": sum(bins.values()),
                "span_s": round(pts[-1][0] - pts[0][0], 6)}

    def window_percentile(self, name: str, window_s: float,
                          p: float) -> Optional[float]:
        d = self.window_hist_delta(name, window_s)
        return sketch_percentile(d["bins"], d["count"], p)

    def window_over_budget(self, name: str, window_s: float,
                           budget_ms: float) -> tuple:
        """(over, total) request counts for the window: how many recorded
        latencies exceeded the budget. Bin-granular: a budget inside a
        bin counts the whole bin as within-budget iff the bin's
        representative value is <= budget (deterministic, ~0.5% relative
        error at the boundary — the sketch's own resolution)."""
        from ..ops.aggs import ddsketch_value
        d = self.window_hist_delta(name, window_s)
        total = d["count"]
        over = sum(c for b, c in d["bins"].items()
                   if float(ddsketch_value(b)) > float(budget_ms))
        return over, total

    def _wall(self, t_mono: float) -> float:
        return self._anchor_wall + (t_mono - self._anchor_mono)

    def stats(self) -> dict:
        """`_nodes/stats` "timeseries" block."""
        with self._ring_lock:
            n = len(self._ring)
            newest = self._ring[-1].t_mono if n else None
            oldest = self._ring[0].t_mono if n else None
        return {"running": self.running,
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "samples": n,
                "ticks": self.ticks,
                "span_s": (round(newest - oldest, 3)
                           if n >= 2 else 0.0),
                "tracked_histograms": sorted(self._track)}


# process-default sampler (one node per process, like METRICS/RECORDER)
SAMPLER = TimeSeriesSampler()
