"""Ingest observatory: the write-path mirror of the query-path telemetry.

Every layer of the write path — bulk accept (`rest/client.py`), ingest
pipelines, the engine writer buffer, refresh with per-stage build
attribution, segment merge + BP reorder, translog, replica write-through
— records into the ONE process registry (`utils/metrics.METRICS`) under
the `indexing.` prefix. This module owns the pieces they share:

- the enable flag (`enabled()` / `set_enabled()`, env
  `OPENSEARCH_TPU_INGEST_OBS`) — the measure_concurrency overhead pair
  toggles it to pin the instrumentation cost;
- the build-stage collector (`stage_scope()` / `note_stage()`): a
  thread-local dict the segment builders and the merge drop wall-time
  attributions into (pack / spill / chunk_merge / quantize /
  device_promote) without threading a parameter through every call —
  `note_stage` is a near-no-op when no refresh is collecting;
- writer-buffer accounting (`buffer_delta`): process-total doc/byte
  gauges summed over every open engine, the write-pressure inputs the
  future defer-merges actuator reads (ROADMAP item 5);
- refresh-to-visible recording: each doc's accept time is stamped at
  writer-buffer append (`Engine.index_doc`) and the accept→searchable
  delta lands in a DDSketch at refresh publish — the honest "how stale
  is search" number, recorded vectorized (`record_many`) so a 64k-doc
  refresh costs one lock acquisition, not 64k;
- the `refresh_stall` flight-recorder trigger (env
  `OPENSEARCH_TPU_REFRESH_STALL_MS`);
- `local_parts` / `merge_parts` / `assemble_block`: the `_nodes/stats`
  `"indexing"` block built from registry wire parts — the SAME assembly
  serves one node and a fleet, so federation (cluster/distnode.py
  `indexing` op) sums counters and gauges and merges DDSketch wire
  forms bin-wise, then computes percentiles from the ONE merged sketch.
  Fleet percentiles are never averages of per-node percentiles.

docs/OBSERVABILITY.md "Ingest observatory" documents the metric and
stage taxonomy; oslint OSL605 (devtools/oslint/ingest_obs_rules.py)
patrols the emission discipline inside `index/` + `ingest/` hot loops.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Sequence

from ..utils.metrics import METRICS, merge_sketches, sketch_snapshot

__all__ = ["enabled", "set_enabled", "stage_scope", "note_stage",
           "buffer_delta", "record_refresh_to_visible", "refresh_stall_ms",
           "refresh_stall", "segment_nbytes", "local_parts", "merge_parts",
           "assemble_block", "reset_buffer_totals", "record_refresh",
           "record_merge", "record_flush", "record_translog_append",
           "record_pipeline", "record_bulk", "count", "doc_bytes",
           "record_replica_sync", "FLUSH_EVERY", "BYTES_SAMPLE"]

PREFIX = "indexing."

# refresh wall times past this threshold freeze a flight-recorder dump
# (reason "refresh_stall", cooldown-limited like other storm-shaped
# triggers)
DEFAULT_REFRESH_STALL_MS = 5_000.0

_enabled_lock = threading.Lock()
_enabled = os.environ.get("OPENSEARCH_TPU_INGEST_OBS", "1") != "0"


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip write-path instrumentation; returns the previous value.
    Engines keep stamping accept times either way (one monotonic read
    per doc — the stamp array must stay parallel to the buffer), but
    nothing is recorded while disabled."""
    global _enabled
    with _enabled_lock:
        prev = _enabled
        _enabled = bool(on)
    return prev


def refresh_stall_ms() -> float:
    return float(os.environ.get("OPENSEARCH_TPU_REFRESH_STALL_MS",
                                DEFAULT_REFRESH_STALL_MS))


# ---------------- build-stage attribution ----------------

_stage_state = threading.local()


@contextlib.contextmanager
def stage_scope():
    """Collect `note_stage` attributions emitted on THIS thread for the
    duration of the scope. Yields the stage->seconds dict. Reentrancy
    (a refresh inside a refresh) keeps the outer collector: attributions
    roll up to the outermost scope, matching how the refresh stage
    partition nests."""
    prev = getattr(_stage_state, "col", None)
    col = prev if prev is not None else {}
    _stage_state.col = col
    try:
        yield col
    finally:
        _stage_state.col = prev


def note_stage(stage: str, seconds: float) -> None:
    """Attribute `seconds` of build wall time to `stage`. No-op (one
    thread-local read) unless a `stage_scope` is active on this thread —
    the builders call this unconditionally; only a collecting refresh
    pays for it."""
    col = getattr(_stage_state, "col", None)
    if col is not None:
        col[stage] = col.get(stage, 0.0) + seconds


# ---------------- writer-buffer accounting ----------------

# per-doc accounting in Engine.index_doc is ONE int add (already
# serialized by the index write lock); byte estimation and the registry
# gauges/counter are folded in every FLUSH_EVERY docs and at refresh,
# sizing at most BYTES_SAMPLE docs sampled from the freshly-appended
# buffer tail and scaling to the fold. Bounded staleness (< FLUSH_EVERY
# docs) and the sampled estimate together buy back the ~10% bulk
# throughput that per-doc emission cost — even one extra Python call
# per accepted doc is measurable at 32 submit threads.
FLUSH_EVERY = 64
BYTES_SAMPLE = 8


def doc_bytes(source) -> int:
    """Cheap structural byte estimate for the writer-buffer gauge —
    O(#fields) over the top level, never a serialization of the doc.
    Called at fold time on a sample of the buffer tail, never per
    accepted doc."""
    est = 24
    for k, v in source.items():
        est += len(k) + 8
        if isinstance(v, str):
            est += len(v)
        elif isinstance(v, (list, tuple)):
            est += 8 * len(v)
    return est


_buf_lock = threading.Lock()
_buf_docs = 0
_buf_bytes = 0


def buffer_delta(docs: int, nbytes: int) -> None:
    """Fold a writer-buffer change (±docs, ±bytes) into the process-total
    gauges `indexing.buffer.docs` / `indexing.buffer.bytes`. Engines add
    per accepted doc and subtract their tracked totals at refresh, so
    the gauges stay consistent across enable toggles mid-buffer."""
    global _buf_docs, _buf_bytes
    with _buf_lock:
        _buf_docs = max(0, _buf_docs + int(docs))
        _buf_bytes = max(0, _buf_bytes + int(nbytes))
        d, b = _buf_docs, _buf_bytes
    METRICS.gauge("indexing.buffer.docs").set(d)
    METRICS.gauge("indexing.buffer.bytes").set(b)


def reset_buffer_totals() -> None:
    """Test/bench isolation: zero the process buffer totals (pairs with
    `MetricsRegistry.reset`, which drops the gauges themselves)."""
    global _buf_docs, _buf_bytes
    with _buf_lock:
        _buf_docs = 0
        _buf_bytes = 0


# ---------------- refresh-to-visible ----------------

def record_refresh_to_visible(index_name: str,
                              accept_stamps: Sequence[float],
                              now_mono: float) -> None:
    """Record accept→searchable deltas for one published refresh: the
    global sketch plus a per-index sketch (cardinality bounded by the
    index count, never the doc count). Vectorized — one `record_many`
    per sketch regardless of the refresh size."""
    if not accept_stamps:
        return
    import numpy as np
    deltas = (now_mono - np.asarray(accept_stamps, np.float64)) * 1000.0
    np.clip(deltas, 0.0, None, out=deltas)
    METRICS.histogram("indexing.refresh_to_visible_ms").record_many(deltas)
    if index_name:
        METRICS.histogram(
            f"indexing.index.{index_name}.refresh_to_visible_ms"
        ).record_many(deltas)


def refresh_stall(index_name: str, total_ms: float,
                  stages: Dict[str, float]) -> None:
    """Freeze a flight-recorder dump for a refresh that blew the stall
    threshold: one `refresh` timeline carrying the stage partition, then
    a cooldown-limited `refresh_stall` trigger."""
    METRICS.counter("indexing.refresh.stalls").inc()
    from .flight_recorder import RECORDER
    if not RECORDER.enabled:
        return
    tl = RECORDER.start("refresh", index=index_name or "_unnamed")
    if tl:
        RECORDER.record(tl, "refresh.stall", total_ms=round(total_ms, 3),
                        stall_threshold_ms=refresh_stall_ms(),
                        **{f"{k}_ms": round(v * 1000.0, 3)
                           for k, v in stages.items()})
        RECORDER.trigger(
            "refresh_stall", [tl],
            note=f"refresh of [{index_name or '_unnamed'}] took "
                 f"{total_ms:.0f}ms (threshold {refresh_stall_ms():.0f}ms)")


# ---------------- emission helpers ----------------
#
# The hot write-path modules (index/, ingest/ — oslint OSL605 scope) call
# ONE guarded helper per event instead of looping over registry lookups
# themselves; every bounded stage/name loop lives here in obs/ (exempt,
# like OSL505).

def record_refresh(index_name: str, ndocs: int, streamed: bool,
                   stamps, build_detail: Dict[str, float],
                   backlog: int) -> None:
    """Fold one published refresh into the registry: totals, the exact
    stage partition (collect/build/publish/merge from boundary stamps
    t0..t4), the builder's stage attributions, and the merge-pressure
    signals. Fires the `refresh_stall` dump past the threshold."""
    t0, t1, t2, t3, t4 = stamps
    total_ms = (t4 - t0) * 1000.0
    METRICS.counter("indexing.refresh.total").inc()
    METRICS.counter("indexing.refresh.docs").inc(int(ndocs))
    if streamed:
        METRICS.counter("indexing.refresh.stream_total").inc()
    METRICS.histogram("indexing.refresh.time_ms").record(total_ms)
    stages = {"collect": t1 - t0, "build": t2 - t1,
              "publish": t3 - t2, "merge": t4 - t3}
    for k, v in stages.items():
        METRICS.histogram(f"indexing.refresh.stage.{k}_ms").record(
            v * 1000.0)
    for k, v in build_detail.items():
        METRICS.histogram(f"indexing.refresh.build.{k}_ms").record(
            v * 1000.0)
    # write-pressure inputs (the defer-merges actuator's future diet):
    # the gauge is "now", the depth sketch is "how it's been" — the
    # merge-backlog burn SLO windows over the sketch
    METRICS.gauge("indexing.merge.backlog").set(int(backlog))
    METRICS.histogram("indexing.merge.backlog_depth").record(float(backlog))
    if total_ms >= refresh_stall_ms():
        refresh_stall(index_name, total_ms, stages)


def record_merge(n_inputs: int, input_docs: int, input_bytes: int,
                 merged, dur_s: float, reorder_s: float,
                 reordered: bool) -> None:
    """One TOP-LEVEL segment merge (nested child merges are part of their
    parent's numbers — merge.py only reports names without a '/')."""
    METRICS.counter("indexing.merge.total").inc()
    METRICS.counter("indexing.merge.input_segments").inc(int(n_inputs))
    METRICS.counter("indexing.merge.input_docs").inc(int(input_docs))
    METRICS.counter("indexing.merge.input_bytes").inc(int(input_bytes))
    METRICS.counter("indexing.merge.output_docs").inc(int(merged.ndocs))
    METRICS.counter("indexing.merge.output_bytes").inc(
        segment_nbytes(merged))
    METRICS.histogram("indexing.merge.time_ms").record(dur_s * 1000.0)
    if reordered:
        METRICS.counter("indexing.merge.reorder_total").inc()
        METRICS.histogram("indexing.merge.reorder_ms").record(
            reorder_s * 1000.0)


def record_flush(dur_ms: float, translog_age_s: float) -> None:
    METRICS.counter("indexing.flush.total").inc()
    METRICS.histogram("indexing.flush.time_ms").record(dur_ms)
    METRICS.gauge("indexing.translog.age_s").set(float(translog_age_s))


def record_translog_append(nbytes: int) -> None:
    METRICS.counter("indexing.translog.ops").inc()
    METRICS.counter("indexing.translog.bytes").inc(int(nbytes))


def record_pipeline(dur_ms: float, dropped: bool) -> None:
    METRICS.counter("indexing.pipeline.docs").inc()
    if dropped:
        METRICS.counter("indexing.pipeline.dropped").inc()
    METRICS.histogram("indexing.pipeline.time_ms").record(dur_ms)


def count(name: str, n: int = 1) -> None:
    """Guarded one-off counter bump for swallowed-exception audit sites
    (`indexing.{stage}.failed` family) — callers pass the full metric
    name; the helper keeps the enabled-check in one place."""
    if _enabled:
        METRICS.counter(name).inc(n)


def record_replica_sync(n: int, dur_ms: float) -> None:
    """Replica adoption after a refresh/force-merge (one wall-time span
    covering all of an index's replica copies)."""
    METRICS.counter("indexing.replica.syncs").inc(int(n))
    METRICS.histogram("indexing.replica.sync_ms").record(dur_ms)


def record_bulk(items: int, nbytes: int, took_ms: float) -> None:
    METRICS.counter("indexing.bulk.requests").inc()
    METRICS.counter("indexing.bulk.items").inc(int(items))
    METRICS.counter("indexing.bulk.bytes").inc(int(nbytes))
    METRICS.histogram("indexing.bulk.took_ms").record(took_ms)


# ---------------- sizes ----------------

def segment_nbytes(seg) -> int:
    """Cheap host-side size of a segment's scoring payload (postings CSR
    arrays + impact planes) — the merge input/output byte accounting.
    Attribute sums only; never touches device residency."""
    total = 0
    for pb in getattr(seg, "postings", {}).values():
        for a in (pb.starts, pb.doc_ids, pb.tfs,
                  pb.pos_starts, pb.positions):
            if a is not None:
                total += int(a.nbytes)
        if pb.impact is not None:
            total += int(pb.impact.nbytes)
    return total


# ---------------- the `_nodes/stats` "indexing" block ----------------

def local_parts(registry=None) -> dict:
    """This node's `indexing.*` slice of the registry in wire form — the
    payload a member answers on the `/_internal` `indexing` op (counters
    and gauges as plain values, histograms as mergeable DDSketch wire)."""
    reg = registry if registry is not None else METRICS
    w = reg.to_wire()
    return {
        "counters": {k: v for k, v in w["counters"].items()
                     if k.startswith(PREFIX)},
        "gauges": {k: v for k, v in w["gauges"].items()
                   if k.startswith(PREFIX)},
        "histograms": {k: v for k, v in w["histograms"].items()
                       if k.startswith(PREFIX)},
    }


def merge_parts(parts_list: Sequence[dict]) -> dict:
    """Fold per-node parts into fleet parts: counters and gauges SUM
    (buffer docs/bytes and merge backlog are extensive quantities — the
    fleet buffer is the sum of node buffers), histograms merge bin-wise
    via `merge_sketches`. Commutative/associative like the PR 10
    federation ops, so member answer order never changes the result."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, List[dict]] = {}
    for p in parts_list:
        if not isinstance(p, dict):
            continue
        for k, v in (p.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (p.get("gauges") or {}).items():
            gauges[k] = gauges.get(k, 0) + v
        for k, w in (p.get("histograms") or {}).items():
            hists.setdefault(k, []).append(w)
    return {"counters": counters, "gauges": gauges,
            "histograms": {k: merge_sketches(ws)
                           for k, ws in sorted(hists.items())}}


_PER_INDEX_SUFFIX = ".refresh_to_visible_ms"
_BUILD_STAGES = ("pack", "spill", "chunk_merge", "quantize",
                 "device_promote")


def assemble_block(parts: dict, nodes: int = 1) -> dict:
    """The `_nodes/stats` `"indexing"` block from wire parts (local or
    fleet-merged — same assembly either way, so a 1-node block and the
    federated block differ only in the numbers). Mirrors the reference
    `_stats` layout: indexing / refresh / merge / flush / translog
    sub-blocks, plus the blocks the reference has no analog for (bulk
    accept, ingest pipelines, writer buffer, replica write-through,
    refresh-to-visible). Percentiles come from `sketch_snapshot` over
    the (possibly merged) sketch — never from averaging."""
    c = parts.get("counters") or {}
    g = parts.get("gauges") or {}
    h = parts.get("histograms") or {}

    def snap(name: str) -> dict:
        w = h.get(name)
        if w is None:
            return {"count": 0, "sum_ms": 0.0, "p50_ms": None,
                    "p95_ms": None, "p99_ms": None}
        return sketch_snapshot(w)

    per_index = {}
    for k in sorted(h):
        if k.startswith("indexing.index.") and k.endswith(_PER_INDEX_SUFFIX):
            idx = k[len("indexing.index."):-len(_PER_INDEX_SUFFIX)]
            per_index[idx] = {"refresh_to_visible_ms": sketch_snapshot(h[k])}

    build_detail = {f"{s}_ms": snap(f"indexing.refresh.build.{s}_ms")
                    for s in _BUILD_STAGES
                    if f"indexing.refresh.build.{s}_ms" in h}

    return {
        "nodes": int(nodes),
        "bulk": {
            "requests": int(c.get("indexing.bulk.requests", 0)),
            "items": int(c.get("indexing.bulk.items", 0)),
            "bytes": int(c.get("indexing.bulk.bytes", 0)),
            "item_failed": int(c.get("indexing.bulk.item_failed", 0)),
            "rejected": int(c.get("indexing.bulk.rejected", 0)),
            "took_ms": snap("indexing.bulk.took_ms"),
        },
        "indexing": {
            "index_total": int(c.get("indexing.docs.indexed", 0)),
            "delete_total": int(c.get("indexing.docs.deleted", 0)),
            "index_failed": int(c.get("indexing.docs.failed", 0)),
        },
        "ingest_pipeline": {
            "docs": int(c.get("indexing.pipeline.docs", 0)),
            "dropped": int(c.get("indexing.pipeline.dropped", 0)),
            "failed": int(c.get("indexing.pipeline.failed", 0)),
            "time_ms": snap("indexing.pipeline.time_ms"),
        },
        "buffer": {
            "docs": int(g.get("indexing.buffer.docs", 0)),
            "bytes": int(g.get("indexing.buffer.bytes", 0)),
        },
        "refresh": {
            "total": int(c.get("indexing.refresh.total", 0)),
            "stream_total": int(c.get("indexing.refresh.stream_total", 0)),
            "docs": int(c.get("indexing.refresh.docs", 0)),
            "stalls": int(c.get("indexing.refresh.stalls", 0)),
            "fanout_failed": int(c.get("indexing.refresh.fanout_failed", 0)),
            "time_ms": snap("indexing.refresh.time_ms"),
            "stages": {
                "collect_ms": snap("indexing.refresh.stage.collect_ms"),
                "build_ms": snap("indexing.refresh.stage.build_ms"),
                "publish_ms": snap("indexing.refresh.stage.publish_ms"),
                "merge_ms": snap("indexing.refresh.stage.merge_ms"),
            },
            "build_detail": build_detail,
            "refresh_to_visible_ms": snap("indexing.refresh_to_visible_ms"),
            "per_index": per_index,
        },
        "merge": {
            "total": int(c.get("indexing.merge.total", 0)),
            "input_segments": int(c.get("indexing.merge.input_segments", 0)),
            "input_docs": int(c.get("indexing.merge.input_docs", 0)),
            "output_docs": int(c.get("indexing.merge.output_docs", 0)),
            "input_bytes": int(c.get("indexing.merge.input_bytes", 0)),
            "output_bytes": int(c.get("indexing.merge.output_bytes", 0)),
            "backlog": int(g.get("indexing.merge.backlog", 0)),
            "time_ms": snap("indexing.merge.time_ms"),
            "reorder": {
                "total": int(c.get("indexing.merge.reorder_total", 0)),
                "time_ms": snap("indexing.merge.reorder_ms"),
            },
        },
        "flush": {
            "total": int(c.get("indexing.flush.total", 0)),
            "remote_failed": int(c.get("indexing.flush.remote_failed", 0)),
            "time_ms": snap("indexing.flush.time_ms"),
        },
        "translog": {
            "ops": int(c.get("indexing.translog.ops", 0)),
            "bytes": int(c.get("indexing.translog.bytes", 0)),
            "age_s": round(float(g.get("indexing.translog.age_s", 0.0)), 3),
        },
        "replica": {
            "syncs": int(c.get("indexing.replica.syncs", 0)),
            "write_through": int(c.get("indexing.replica.write_through", 0)),
            "failed": int(c.get("indexing.replica.failed", 0)),
            "sync_ms": snap("indexing.replica.sync_ms"),
            "fanout_ms": snap("indexing.replica.fanout_ms"),
        },
    }
