"""`_nodes/hot_threads`: a Python-side stack sampler.

Reference analog: `monitor/jvm/HotThreads.java` — sample every thread's
stack a few times over a short interval, coalesce identical stacks, and
report the busiest ones with idle threads filtered out. Here the stacks
come from `sys._current_frames()` (the CPython equivalent of the JVM's
ThreadMXBean dump), the interesting threads are this runtime's named
actors — the serving scheduler's dispatcher and completion worker
(`ostpu-serving-*`), the named host pools (`ostpu-search-*` etc.) — plus
whatever HTTP request threads are mid-search.

Idle filtering drops threads whose every snapshot parks in a known wait
site (condition/event waits, selector polls, executor queue gets) —
EXCEPT the runtime's own `ostpu-*` threads, which are always reported
(their parked-ness is exactly what an operator diagnosing a wedge needs
to see) with an `idle=true` annotation.

The inter-snapshot sleep is a sampling interval, not a poll-for-condition
loop — OSL503's no-sleep-polling rule patrols coordination code
(serving/, utils/, rest/), not samplers."""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

# (filename suffix, function name) pairs that mean "parked, not working".
# A thread is idle when its INNERMOST frame matches one of these.
_IDLE_SITES = (
    ("threading.py", "wait"),
    ("threading.py", "_wait_for_tstate_lock"),
    ("selectors.py", "select"),
    ("socketserver.py", "serve_forever"),
    ("socketserver.py", "get_request"),
    ("socket.py", "accept"),
    ("thread.py", "_worker"),      # concurrent.futures executor idle
    ("queue.py", "get"),
)


def _is_idle_stack(stack: List[traceback.FrameSummary]) -> bool:
    if not stack:
        return True
    top = stack[-1]
    fn = top.filename or ""
    for suffix, name in _IDLE_SITES:
        if fn.endswith(suffix) and top.name == name:
            return True
    return False


def _sample(own_ident: int) -> Dict[int, List[traceback.FrameSummary]]:
    frames = sys._current_frames()
    out = {}
    for ident, frame in frames.items():
        if ident == own_ident:
            continue
        out[ident] = traceback.extract_stack(frame)
    return out


def hot_threads(node_name: str = "node", snapshots: int = 3,
                interval_s: float = 0.02, ignore_idle: bool = True,
                as_json: bool = False):
    """Sample live thread stacks `snapshots` times, `interval_s` apart.

    Returns the OpenSearch-flavoured plain-text report (default) or, with
    as_json=True, a list of per-thread dicts:
    {"name", "ident", "snapshots", "seen", "idle", "stack": [...]}
    where `stack` is the thread's most frequent sampled stack, innermost
    frame last."""
    snapshots = max(1, min(int(snapshots), 10))
    interval_s = max(0.0, min(float(interval_s), 1.0))
    own = threading.get_ident()
    samples: List[Dict[int, List[traceback.FrameSummary]]] = []
    for i in range(snapshots):
        if i:
            time.sleep(interval_s)
        samples.append(_sample(own))

    names = {t.ident: t.name for t in threading.enumerate()}
    idents = sorted({i for s in samples for i in s},
                    key=lambda i: (not names.get(i, "").startswith("ostpu-"),
                                   names.get(i, ""), i))
    threads = []
    for ident in idents:
        stacks = [s[ident] for s in samples if ident in s]
        # most frequent stack wins (ties: the latest)
        keyed: Dict[Tuple, List] = {}
        counts: Dict[Tuple, int] = {}
        for st in stacks:
            key = tuple((f.filename, f.lineno, f.name) for f in st)
            keyed[key] = st
            counts[key] = counts.get(key, 0) + 1
        best_key = max(counts, key=lambda k: counts[k])
        best = keyed[best_key]
        idle = all(_is_idle_stack(st) for st in stacks)
        name = names.get(ident, f"thread-{ident}")
        if idle and ignore_idle and not name.startswith("ostpu-"):
            continue
        threads.append({
            "name": name, "ident": ident,
            "snapshots": snapshots, "seen": counts[best_key],
            "idle": idle,
            "stack": [{"file": f.filename, "line": f.lineno,
                       "function": f.name,
                       **({"code": f.line} if f.line else {})}
                      for f in best],
        })
    if as_json:
        return threads

    lines = [f"::: {{{node_name}}}",
             f"   Hot threads: {snapshots} snapshots, "
             f"interval={interval_s * 1000:.0f}ms, "
             f"ignore_idle={str(ignore_idle).lower()}, "
             f"threads={len(threads)}", ""]
    for t in threads:
        state = "idle/waiting" if t["idle"] else "busy"
        lines.append(f"   '{t['name']}' id={t['ident']} "
                     f"{t['seen']}/{t['snapshots']} snapshots sharing "
                     f"following stack ({state}):")
        for f in t["stack"]:
            lines.append(f"     {f['file']}:{f['line']} {f['function']}"
                         + (f"  | {f['code']}" if f.get("code") else ""))
        lines.append("")
    return "\n".join(lines)
