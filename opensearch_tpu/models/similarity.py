"""Similarity (scoring) models. Analog of reference
`index/similarity/SimilarityService.java` which wraps Lucene's
BM25Similarity / ClassicSimilarity / BooleanSimilarity / LMDirichletSimilarity.

A Similarity contributes two things:
- a host-side per-term weight (idf × boost — collection-level statistics,
  computed index-wide across segments like Lucene's CollectionStatistics),
- the static `sim_id` + scalar params consumed by the traced per-posting
  formula in `ops.scoring.posting_contrib`.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ops.scoring import (SIM_BM25, SIM_BOOLEAN, SIM_CLASSIC, SIM_LM_DIRICHLET,
                           bm25_idf, classic_idf)


@dataclass(frozen=True)
class Similarity:
    sim_id: int
    k1: float = 1.2
    b: float = 0.75

    def term_weight(self, boost: float, n_docs: int, df: int) -> float:
        raise NotImplementedError

    def term_aux(self, cf: float, total_tf: float) -> float:
        """Per-term auxiliary scalar (collection LM probability for Dirichlet)."""
        return 0.0

    @property
    def uses_norms(self) -> bool:
        return True


@dataclass(frozen=True)
class BM25(Similarity):
    """BM25 with Lucene's idf and tf saturation (reference BM25Similarity;
    default k1=1.2 b=0.75 per IndexSettings)."""

    sim_id: int = SIM_BM25

    def term_weight(self, boost: float, n_docs: int, df: int) -> float:
        return boost * bm25_idf(n_docs, df)


@dataclass(frozen=True)
class Classic(Similarity):
    sim_id: int = SIM_CLASSIC

    def term_weight(self, boost: float, n_docs: int, df: int) -> float:
        idf = classic_idf(n_docs, df)
        return boost * idf * idf


@dataclass(frozen=True)
class Boolean(Similarity):
    sim_id: int = SIM_BOOLEAN

    def term_weight(self, boost: float, n_docs: int, df: int) -> float:
        return boost

    @property
    def uses_norms(self) -> bool:
        return False


@dataclass(frozen=True)
class LMDirichlet(Similarity):
    """LM with Dirichlet smoothing; k1 carries mu (default 2000 like Lucene)."""

    sim_id: int = SIM_LM_DIRICHLET
    k1: float = 2000.0

    def term_weight(self, boost: float, n_docs: int, df: int) -> float:
        return boost

    def term_aux(self, cf: float, total_tf: float) -> float:
        return max(cf, 1.0) / max(total_tf, 1.0)


def resolve_similarity(cfg) -> Similarity:
    """Index-settings similarity resolution (reference SimilarityService
    built-ins: BM25 (default), boolean, classic, LMDirichlet)."""
    if cfg is None:
        return BM25()
    if isinstance(cfg, Similarity):
        return cfg
    if isinstance(cfg, str):
        cfg = {"type": cfg}
    t = cfg.get("type", "BM25").lower()
    if t == "bm25":
        return BM25(k1=float(cfg.get("k1", 1.2)), b=float(cfg.get("b", 0.75)))
    if t == "classic":
        return Classic()
    if t == "boolean":
        return Boolean()
    if t in ("lmdirichlet", "lm_dirichlet"):
        return LMDirichlet(k1=float(cfg.get("mu", 2000.0)))
    raise ValueError(f"unknown similarity [{t}]")
