from .similarity import BM25, Boolean, Classic, LMDirichlet, Similarity, resolve_similarity

__all__ = ["Similarity", "BM25", "Classic", "Boolean", "LMDirichlet", "resolve_similarity"]
