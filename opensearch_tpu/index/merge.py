"""Segment merging. Analog of reference `OpenSearchTieredMergePolicy.java` +
Lucene's SegmentMerger, rebuilt as vectorized multiway sorted-run merges over
CSR arrays (deleted docs are compacted away, exactly like Lucene merges).
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ..obs import ingest_obs as _iobs
from ..ops import device_merge
from .segment import (CODEC_V2, GeoColumn, KeywordColumn, NumericColumn,
                      PostingsBlock, Segment, TextFieldStats, VectorColumn,
                      default_codec_version)


class TieredMergePolicy:
    """Size-tiered selection: merge when >= `segments_per_tier` segments share
    a size tier (by live doc count), preferring the smallest."""

    def __init__(self, segments_per_tier: int = 8, max_merged_docs: int = 1 << 24):
        self.segments_per_tier = segments_per_tier
        self.max_merged_docs = max_merged_docs

    def find_merges(self, segments: List[Segment]) -> List[List[Segment]]:
        candidates = [s for s in segments if s.live_count < self.max_merged_docs]
        if len(candidates) < self.segments_per_tier:
            # also merge when deletes dominate (reference: forceMergeDeletes)
            heavy = [s for s in segments
                     if s.ndocs > 0 and s.live_count < 0.5 * s.ndocs]
            return [[s] for s in heavy]
        candidates.sort(key=lambda s: s.live_count)
        return [candidates[: self.segments_per_tier]]


def merge_segments(name: str, segments: List[Segment]) -> Segment:
    """Compacting multiway merge of N segments into one."""
    # instrumentation is TOP-LEVEL only: nested child merges (name
    # carries a "/") recurse through here and their wall time / sizes
    # are already inside the parent's numbers
    _obs = "/" not in name and _iobs.enabled()
    _t0 = time.perf_counter()
    _in_bytes = sum(_iobs.segment_nbytes(s) for s in segments) if _obs else 0
    live_masks = [s.live.astype(bool) for s in segments]
    live_counts = [int(m.sum()) for m in live_masks]
    ndocs = sum(live_counts)
    # old (seg, doc) -> new doc id
    doc_maps: List[np.ndarray] = []
    base = 0
    for s, m, c in zip(segments, live_masks, live_counts):
        dmap = np.full(s.ndocs, -1, dtype=np.int64)
        dmap[m] = base + np.arange(c, dtype=np.int64)
        doc_maps.append(dmap)
        base += c

    ids: List[str] = []
    sources: List[dict] = []
    stored_vals: List = []
    any_stored = any(getattr(s, "stored_vals", None) for s in segments)
    tv_fields = {f for s in segments
                 for f in (getattr(s, "term_vectors", None) or {})}
    term_vectors = {f: [] for f in tv_fields}
    seq_nos = np.empty(ndocs, dtype=np.int64)
    for s, m, dmap in zip(segments, live_masks, doc_maps):
        stv = getattr(s, "term_vectors", None) or {}
        for old in np.nonzero(m)[0]:
            ids.append(s.ids[old])
            sources.append(s.sources[old])
            if any_stored:
                stored_vals.append(s.stored_vals[old]
                                   if s.stored_vals else None)
            for f in tv_fields:
                col = stv.get(f)
                term_vectors[f].append(col[old] if col else None)
        seq_nos[dmap[m]] = s.seq_nos[m]

    # ---- postings ----
    post_fields = {f for s in segments for f in s.postings}
    postings: Dict[str, PostingsBlock] = {}
    for f in post_fields:
        vocab_union = sorted({t for s in segments if f in s.postings for t in s.postings[f].vocab})
        new_row_of = {t: i for i, t in enumerate(vocab_union)}
        rows_parts, docs_parts, tfs_parts, pos_len_parts, pos_parts = [], [], [], [], []
        has_positions = all(f not in s.postings or s.postings[f].pos_starts is not None
                            for s in segments)
        for s, dmap in zip(segments, doc_maps):
            pb = s.postings.get(f)
            if pb is None or pb.size == 0:
                continue
            lens = np.diff(pb.starts)
            row_map = np.fromiter((new_row_of[t] for t in pb.vocab), dtype=np.int64,
                                  count=len(pb.vocab))
            rows = np.repeat(row_map, lens)
            new_docs = dmap[pb.doc_ids]
            keep = new_docs >= 0
            rows_parts.append(rows[keep])
            docs_parts.append(new_docs[keep])
            tfs_parts.append(pb.tfs[keep])
            if has_positions and pb.pos_starts is not None:
                plens = np.diff(pb.pos_starts)[keep]
                pos_len_parts.append(plens)
                # gather each kept posting's position run
                kept_starts = pb.pos_starts[:-1][keep]
                idx = _ranges_gather(kept_starts, plens)
                pos_parts.append(pb.positions[idx])
        if not rows_parts:
            continue
        rows = np.concatenate(rows_parts)
        docs = np.concatenate(docs_parts)
        tfs = np.concatenate(tfs_parts)
        starts = np.zeros(len(vocab_union) + 1, dtype=np.int64)
        if device_merge.use_device_merge(len(rows)):
            # the O(P log P) multiway sorted-run merge runs on device
            # (ops/device_merge.py); `order` drives the host position
            # regather so results stay bit-identical to the numpy path
            _r, d32, t32, order, counts = device_merge.merge_sorted_runs(
                rows, docs, tfs, len(vocab_union))
            docs, tfs = d32.astype(np.int64), t32
            order = order.astype(np.int64)
            np.cumsum(counts.astype(np.int64), out=starts[1:])
        else:
            order = np.lexsort((docs, rows))
            rows, docs, tfs = rows[order], docs[order], tfs[order]
            np.cumsum(np.bincount(rows, minlength=len(vocab_union)),
                      out=starts[1:])
        pos_starts = positions = None
        if has_positions and pos_len_parts:
            plens = np.concatenate(pos_len_parts)[order]
            all_pos_parts = np.concatenate(pos_parts) if pos_parts else np.empty(0, np.int32)
            # positions were concatenated in pre-sort posting order; regather
            pre_starts = np.zeros(len(plens) + 1, dtype=np.int64)
            np.cumsum(np.concatenate(pos_len_parts), out=pre_starts[1:])
            idx = _ranges_gather(pre_starts[:-1][order], plens)
            positions = all_pos_parts[idx]
            pos_starts = np.zeros(len(plens) + 1, dtype=np.int64)
            np.cumsum(plens, out=pos_starts[1:])
        postings[f] = PostingsBlock(f, vocab_union, new_row_of, starts,
                                    docs.astype(np.int32), tfs.astype(np.float32),
                                    pos_starts, positions)

    # ---- numeric columns ----
    numeric_cols: Dict[str, NumericColumn] = {}
    for f in {f for s in segments for f in s.numeric_cols}:
        kind = next(s.numeric_cols[f].kind for s in segments if f in s.numeric_cols)
        dtype = np.float64 if kind == "float" else np.int64
        values = np.zeros(ndocs, dtype=dtype)
        present = np.zeros(ndocs, dtype=bool)
        for s, m, dmap in zip(segments, live_masks, doc_maps):
            col = s.numeric_cols.get(f)
            if col is None:
                continue
            values[dmap[m]] = col.values[m]
            present[dmap[m]] = col.present[m]
        numeric_cols[f] = NumericColumn(f, kind, values, present)

    # ---- keyword columns ----
    keyword_cols: Dict[str, KeywordColumn] = {}
    for f in {f for s in segments for f in s.keyword_cols}:
        vocab_union = sorted({v for s in segments if f in s.keyword_cols
                              for v in s.keyword_cols[f].vocab})
        new_ord_of = {v: i for i, v in enumerate(vocab_union)}
        doc_parts, ord_parts = [], []
        for s, dmap in zip(segments, doc_maps):
            col = s.keyword_cols.get(f)
            if col is None or len(col.ords) == 0:
                continue
            remap = np.fromiter((new_ord_of[v] for v in col.vocab), dtype=np.int64,
                                count=len(col.vocab))
            new_docs = dmap[col.doc_of_value]
            keep = new_docs >= 0
            doc_parts.append(new_docs[keep])
            ord_parts.append(remap[col.ords[keep]])
        if doc_parts:
            docs = np.concatenate(doc_parts)
            ords = np.concatenate(ord_parts)
            order = np.lexsort((ords, docs))
            docs, ords = docs[order], ords[order]
        else:
            docs = np.empty(0, np.int64)
            ords = np.empty(0, np.int64)
        starts = np.zeros(ndocs + 1, dtype=np.int64)
        np.cumsum(np.bincount(docs, minlength=ndocs), out=starts[1:])
        min_ord = np.full(ndocs, -1, dtype=np.int32)
        if len(docs):
            first = np.unique(docs, return_index=True)
            min_ord[first[0]] = ords[first[1]].astype(np.int32)
        keyword_cols[f] = KeywordColumn(f, vocab_union, starts, ords.astype(np.int32),
                                        docs.astype(np.int32), min_ord)

    # ---- geo columns ----
    geo_cols: Dict[str, GeoColumn] = {}
    for f in {f for s in segments for f in s.geo_cols}:
        lat = np.zeros(ndocs, dtype=np.float32)
        lon = np.zeros(ndocs, dtype=np.float32)
        present = np.zeros(ndocs, dtype=bool)
        for s, m, dmap in zip(segments, live_masks, doc_maps):
            col = s.geo_cols.get(f)
            if col is None:
                continue
            lat[dmap[m]] = col.lat[m]
            lon[dmap[m]] = col.lon[m]
            present[dmap[m]] = col.present[m]
        geo_cols[f] = GeoColumn(f, lat, lon, present)

    # ---- vector columns ----
    vector_cols: Dict[str, VectorColumn] = {}
    for f in {f for s in segments for f in getattr(s, "vector_cols", {})}:
        first = next(s.vector_cols[f] for s in segments if f in s.vector_cols)
        dims = first.values.shape[1]
        values = np.zeros((ndocs, dims), np.float32)
        present = np.zeros(ndocs, bool)
        for s, m, dmap in zip(segments, live_masks, doc_maps):
            col = s.vector_cols.get(f)
            if col is None:
                continue
            values[dmap[m]] = col.values[m]
            present[dmap[m]] = col.present[m]
        vector_cols[f] = VectorColumn(f, values, present, first.similarity,
                                      method=first.method)

    # ---- shape columns ----
    shape_cols = {}
    for f in {f for s in segments for f in getattr(s, "shape_cols", {})}:
        from .segment import ShapeColumn
        specs: list = [None] * ndocs
        minx = np.full(ndocs, np.inf)
        miny = np.full(ndocs, np.inf)
        maxx = np.full(ndocs, -np.inf)
        maxy = np.full(ndocs, -np.inf)
        present = np.zeros(ndocs, bool)
        for s, m, dmap in zip(segments, live_masks, doc_maps):
            col = s.shape_cols.get(f)
            if col is None:
                continue
            tgt = dmap[m]
            for old_i, new_i in zip(np.nonzero(m)[0], tgt):
                specs[new_i] = col.specs[old_i]
            minx[tgt] = col.minx[m]
            miny[tgt] = col.miny[m]
            maxx[tgt] = col.maxx[m]
            maxy[tgt] = col.maxy[m]
            present[tgt] = col.present[m]
        shape_cols[f] = ShapeColumn(f, specs, minx, miny, maxx, maxy, present)

    # ---- doc lens + stats ----
    doc_lens: Dict[str, np.ndarray] = {}
    text_stats: Dict[str, TextFieldStats] = {}
    for f in {f for s in segments for f in s.doc_lens}:
        dl = np.zeros(ndocs, dtype=np.int64)
        for s, m, dmap in zip(segments, live_masks, doc_maps):
            sdl = s.doc_lens.get(f)
            if sdl is not None:
                dl[dmap[m]] = sdl[m]
        doc_lens[f] = dl
        text_stats[f] = TextFieldStats(doc_count=int((dl > 0).sum()), sum_dl=int(dl.sum()))

    # ---- nested blocks: drop children of deleted parents, remap parent ids ----
    nested = {}
    for npath in sorted({p for s in segments for p in s.nested}):
        child_segs: List[Segment] = []
        saved_lives: List[np.ndarray] = []
        new_parent_parts: List[np.ndarray] = []
        for s, dmap in zip(segments, doc_maps):
            blk = s.nested.get(npath)
            if blk is None or blk.child.ndocs == 0:
                continue
            keep = (dmap[blk.parent_of] >= 0) & blk.child.live
            saved_lives.append(blk.child.live)
            blk.child.live = keep  # temporary: drives the child compaction
            child_segs.append(blk.child)
            new_parent_parts.append(dmap[blk.parent_of[keep]].astype(np.int32))
        if not child_segs:
            continue
        try:
            merged_child = merge_segments(f"{name}/{npath}", child_segs)
        finally:
            for cs, old in zip(child_segs, saved_lives):
                cs.live = old
        parent_of = (np.concatenate(new_parent_parts) if new_parent_parts
                     else np.empty(0, np.int32))
        from .segment import NestedBlock
        nested[npath] = NestedBlock(merged_child, parent_of)

    merged = Segment(name, ndocs, postings, numeric_cols, keyword_cols,
                     geo_cols, doc_lens, text_stats, ids, sources,
                     seq_nos=seq_nos, vector_cols=vector_cols, nested=nested,
                     shape_cols=shape_cols,
                     stored_vals=stored_vals if any_stored else None)
    merged.term_vectors = term_vectors if tv_fields else None
    if any(s.__dict__.get("_reordered") for s in segments):
        # a BP-reordered input sits in the concatenation in PERMUTED
        # order, so the merged segment's internal ids no longer encode
        # arrival — thread the inputs' arrival planes through (offset per
        # input, live-compacted) or exact-score ties in the merged
        # segment break differently from the unreordered arm's merge of
        # the same corpus (the cross-arm parity contract). Values only
        # need to be order-preserving, not dense.
        parts = []
        offset = 0
        for s, m in zip(segments, live_masks):
            r = s.tie_ranks()
            if r is None:
                r = np.arange(s.ndocs, dtype=np.int64)
            parts.append(r[m] + offset)
            offset += s.ndocs
        merged.__dict__["_tie_rank"] = np.concatenate(parts) if parts \
            else np.zeros(0, np.int64)
        merged.__dict__["_reordered"] = True
    # codec propagation: merges emit the PROCESS-DEFAULT codec — they are
    # the natural rebuild point for the format rev (a v1+v2 merge
    # upgrades the v1 half; under the OPENSEARCH_TPU_CODEC=1 rollback pin
    # every merge demotes to v1, so the index converges back). Impacts
    # are REBUILT from the merged tf/doc-len planes (the merged field's
    # avgdl differs from every input's, so carried quantized values would
    # bake a stale norm); the O(P) quantize map itself runs on device
    # past the size threshold (ops/device_merge.quantize_impacts).
    _reorder_s = 0.0
    _reordered = False
    if default_codec_version() >= CODEC_V2:
        # feature planes (rank_features index_impacts opt-in) rebuild
        # whenever ANY input carried one for the field — the opt-in
        # travels with the data, so merges never need the mappings
        ffields = {f for s in segments for f, pb in s.postings.items()
                   if pb.impact is not None and pb.impact.kind == "feature"}
        _q0 = time.perf_counter()
        merged.build_impacts(feature_fields=ffields)
        _iobs.note_stage("quantize", time.perf_counter() - _q0)
        if "/" not in name:
            # BP-style impact-clustered doc-id reordering (index/reorder.py):
            # merges are the one point the whole doc set is in hand and the
            # impact planes are fresh — nested CHILD merges (name carries a
            # "/") skip, because the parent's apply_permutation re-sorts
            # children against the permuted parent ids itself. The pass is
            # deterministic, so copy holders re-running this merge stay
            # byte-identical (PR-9 replication contract).
            from .reorder import maybe_reorder
            _r0 = time.perf_counter()
            _pre = merged
            merged = maybe_reorder(merged)
            _reorder_s = time.perf_counter() - _r0
            _reordered = merged is not _pre
    if _obs:
        # input counts pre-compaction (deleted docs included) so
        # input_docs - output_docs reads as "deletes reclaimed"
        _iobs.record_merge(len(segments), sum(s.ndocs for s in segments),
                           _in_bytes, merged, time.perf_counter() - _t0,
                           _reorder_s, _reordered)
    return merged


def _ranges_gather(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Indices selecting [starts[i], starts[i]+lens[i]) runs, concatenated —
    the vectorized run-gather underlying positional merges."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    ends = np.cumsum(lens)
    idx = np.arange(total, dtype=np.int64)
    run = np.searchsorted(ends, idx, side="right")
    prev = np.concatenate(([0], ends[:-1]))
    return starts[run] + (idx - prev[run])
