"""BP-style impact-clustered doc-id reordering (codec v2 merge pass).

Block-max pruning (search/impactpath.py, ops/pallas_bm25 impact kernel)
prices every 128-posting block at `w_t · scale · block_max` and skips the
cheap ones. On a corpus indexed in arrival order the per-block maxima are
near-uniform — every block of a queried term contains SOME high-impact
posting — so only skewed/single-term query shapes ever skip (0.58 skip
rate on the BENCH_r06 synthetic; equal-idf multi-term mixes skip ~0).
Reordering doc ids so documents with similar high-impact terms are
ADJACENT concentrates each term's impact mass into few blocks, which is
the classic block-max force multiplier (recursive graph bisection /
"BP", Dhulipala et al. KDD'16; BM25S eager impacts, arxiv 2407.03618;
GPUSparse block metadata, arxiv 2606.26441).

The pass runs at merge time (index/merge.py), AFTER the merged impact
planes are built, and has three stages:

1. **Signature construction.** One field carries the clustering signal:
   the largest codec-v2 text field. Terms are filtered to the
   informative band (df >= REORDER_MIN_DF, df <= ndocs/2 — ubiquitous
   terms discriminate nothing and cost the most) and capped by
   cumulative postings (REORDER_MAX_POSTINGS × ndocs) / term count
   (REORDER_MAX_TERMS), richest-df first. Each doc's signature is its
   sparse (term -> dequantized impact) vector over that band — the
   *impact* weighting is what distinguishes this from plain BP: two docs
   sharing a term at high impact pull together harder than two sharing
   it at tf=1 in a long doc.
2. **Recursive bisection.** Each node splits its doc range in half and
   runs swap passes: per term, the weighted log-gap cost delta of moving
   one posting across the cut; per doc, the impact-weighted sum over its
   signature; the two half-orders pair off best-gain-first and swap
   while the pair gain is positive. Stable sorts + arrival-order
   tie-breaks keep the whole pass DETERMINISTIC — replicas re-running
   the same merge produce byte-identical segments (the PR-9 replication
   contract). Cost: O(levels · passes · P_sig) with
   levels = log2(ndocs/leaf); the defaults bound P_sig by 8·ndocs so the
   pass is ~linear in the corpus and strictly merge-time (never on the
   query path).
3. **Permutation application.** `apply_permutation` rebuilds the segment
   wholesale: postings doc ids are remapped and re-sorted per row (the
   O(P log P) sort rides ops/device_merge.merge_sorted_runs past the
   device threshold — the same two-key lax.sort the merge itself uses),
   positions regathered, quantized impact planes PERMUTED (the (tf, dl)
   multiset per term is invariant, so q and scale carry over; only the
   block-max sidecar is recomputed over the new layout), doc-value
   columns / stored fields / _ids / seq_nos / nested blocks remapped.
   Query-time scoring is doc-id-agnostic, so the host oracle and every
   serving tier see the same pages (tests/test_reorder.py pins parity
   across refresh and replica failover).

Skipped when: the segment is below REORDER_MIN_DOCS (block pruning can't
win anything under a few hundred blocks), no codec-v2 impact plane
exists (v1 segments), the signature band is empty, or
OPENSEARCH_TPU_REORDER=0 pins the pass off (rollback / ablation knob —
the bench A/B runs both arms through it).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..utils.metrics import METRICS
from .segment import (CODEC_V1, CODEC_V2, IMPACT_BLOCK, KeywordColumn,
                      NestedBlock, NumericColumn, PostingsBlock, Segment)

# signature band + cost knobs (docs/CODEC.md documents the model)
REORDER_MIN_DOCS = 1 << 15     # below this, dense scoring is already cheap
REORDER_MIN_DF = 4             # rarer terms: no block to cluster
REORDER_MAX_DENSITY = 8        # terms on > ndocs/8 docs carry no signal:
#                                they appear in most blocks whatever the
#                                order, and would eat the posting budget
#                                that buys mid-df concentration
REORDER_MAX_TERMS = 8192       # signature width cap
REORDER_MAX_POSTINGS = 12      # x ndocs: signature posting-mass cap
REORDER_LEAF = IMPACT_BLOCK    # stop splitting at one block of docs
REORDER_PASSES = 6             # swap passes per bisection node
REORDER_MAX_DEPTH = 20         # hard recursion bound (2^20 leaves)
_GAIN_TOL = 1e-9               # zero-gain swaps would oscillate forever


def enabled() -> bool:
    return os.environ.get("OPENSEARCH_TPU_REORDER", "1") != "0"


def min_docs() -> int:
    return int(os.environ.get("OPENSEARCH_TPU_REORDER_MIN_DOCS",
                              REORDER_MIN_DOCS))


def _pick_field(seg: Segment) -> Optional[str]:
    """The clustering signal field: the largest codec-v2 text plane."""
    best = None
    best_size = 0
    for f, pb in seg.postings.items():
        if pb.impact is None or f not in seg.doc_lens:
            continue
        if pb.size > best_size:
            best, best_size = f, pb.size
    return best


def _signature(seg: Segment, field: str
               ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Doc-major sparse impact signatures over the informative term band.

    -> (dstarts i64[ndocs+1], feat i32[Psig], w f32[Psig]) with postings
    doc-contiguous, or None when the band is empty."""
    from ..ops.scoring import dequant_impact_np

    pb = seg.postings[field]
    plane = pb.impact
    lens = np.diff(pb.starts)
    band = np.nonzero((lens >= REORDER_MIN_DF)
                      & (lens <= max(seg.ndocs // REORDER_MAX_DENSITY,
                                     1)))[0]
    if not len(band):
        return None
    # richest-df first under the posting-mass + width caps: high-df terms
    # span the most blocks, so clustering them pays the most skips
    order = band[np.argsort(-lens[band], kind="stable")]
    cum = np.cumsum(lens[order])
    budget = REORDER_MAX_POSTINGS * seg.ndocs
    keep_n = int(np.searchsorted(cum, budget, side="right"))
    keep_n = max(1, min(keep_n, REORDER_MAX_TERMS))
    sel = order[:keep_n]

    docs_l: List[np.ndarray] = []
    feat_l: List[np.ndarray] = []
    w_l: List[np.ndarray] = []
    for fi, r in enumerate(sel):
        a, b = int(pb.starts[r]), int(pb.starts[r + 1])
        docs_l.append(pb.doc_ids[a:b].astype(np.int64))
        feat_l.append(np.full(b - a, fi, np.int32))
        w_l.append(dequant_impact_np(plane.q[a:b], plane.scale))
    docs = np.concatenate(docs_l)
    feat = np.concatenate(feat_l)
    w = np.concatenate(w_l).astype(np.float32)
    # doc-major: stable sort keeps each doc's features df-descending,
    # a deterministic but irrelevant inner order
    o = np.argsort(docs, kind="stable")
    docs, feat, w = docs[o], feat[o], w[o]
    dstarts = np.zeros(seg.ndocs + 1, np.int64)
    np.cumsum(np.bincount(docs, minlength=seg.ndocs), out=dstarts[1:])
    return dstarts, feat, w


def _ranges_gather(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    from .merge import _ranges_gather as rg
    return rg(starts, lens)


def _gap_cost(deg: np.ndarray, n: int) -> np.ndarray:
    """Weighted log-gap cost of one side: deg · log2((n+1)/(deg+1)) — the
    BP objective with impact mass standing in for posting counts."""
    return deg * np.log2((n + 1.0) / (deg + 1.0))


def _node_passes(docs: np.ndarray, dstarts: np.ndarray, feat: np.ndarray,
                 w: np.ndarray, nfeat: int, passes: int
                 ) -> Tuple[np.ndarray, int]:
    """Run the swap passes of one bisection node; returns the improved
    doc order (L half then R half) and the FIRST pass's swap count (the
    purity signal: a node whose first pass moves almost nothing is
    already one cluster and bisects no further)."""
    n = len(docs)
    half = n // 2
    L = docs[:half].copy()
    R = docs[half:].copy()
    dlens = np.diff(dstarts)
    first_k = 0
    for it in range(passes):
        idxL = _ranges_gather(dstarts[L], dlens[L])
        idxR = _ranges_gather(dstarts[R], dlens[R])
        fL, wL = feat[idxL], w[idxL]
        fR, wR = feat[idxR], w[idxR]
        degL = np.bincount(fL, weights=wL, minlength=nfeat)
        degR = np.bincount(fR, weights=wR, minlength=nfeat)
        base = _gap_cost(degL, len(L)) + _gap_cost(degR, len(R))
        # unit-move delta (clamped: weighted mass can sit below 1), the
        # standard BP approximation scaled per posting by its impact
        gainT_L = base - (_gap_cost(np.maximum(degL - 1.0, 0.0), len(L))
                          + _gap_cost(degR + 1.0, len(R)))
        gainT_R = base - (_gap_cost(np.maximum(degR - 1.0, 0.0), len(R))
                          + _gap_cost(degL + 1.0, len(L)))
        runL = np.repeat(np.arange(len(L)), dlens[L])
        runR = np.repeat(np.arange(len(R)), dlens[R])
        gL = np.bincount(runL, weights=wL * gainT_L[fL], minlength=len(L))
        gR = np.bincount(runR, weights=wR * gainT_R[fR], minlength=len(R))
        oL = np.argsort(-gL, kind="stable")
        oR = np.argsort(-gR, kind="stable")
        m = min(len(oL), len(oR))
        pair = gL[oL[:m]] + gR[oR[:m]]
        k = int((pair > _GAIN_TOL).sum())
        if it == 0:
            first_k = k
        if k == 0:
            break
        swapL = oL[:k]
        swapR = oR[:k]
        L[swapL], R[swapR] = R[swapR], L[swapL].copy()
    return np.concatenate([L, R]), first_k


def compute_permutation(seg: Segment, field: Optional[str] = None,
                        leaf: int = REORDER_LEAF,
                        passes: int = REORDER_PASSES
                        ) -> Optional[np.ndarray]:
    """-> new_order i64[ndocs] (new doc id -> old doc id), or None when
    the segment is ineligible (no v2 plane / empty signature band)."""
    if field is None:
        field = _pick_field(seg)
    if field is None:
        return None
    sig = _signature(seg, field)
    if sig is None:
        return None
    dstarts, feat, w = sig
    nfeat = int(feat.max()) + 1 if len(feat) else 0
    if nfeat == 0:
        return None
    # per-doc mean signature impact — the IMPACT-stratification key.
    # Bisection clusters docs by shared terms (presence); once a node is
    # one cluster the presence objective is flat and further splitting
    # is noise — sorting the converged node by this key instead lays its
    # docs out hot -> cold, so every term's postings inside the cluster
    # carry a monotone impact gradient and the tail BLOCKS (uniformly
    # low block_max) become prunable. This is the "impact-clustered"
    # half of the pass: BP alone concentrates terms into ranges but
    # leaves intra-cluster impacts i.i.d. — measured, that skips
    # nothing, because every block still contains one hot posting.
    cnt = np.diff(dstarts).astype(np.float64)
    dsum = np.zeros(seg.ndocs, np.float64)
    np.add.at(dsum, np.repeat(np.arange(seg.ndocs), np.diff(dstarts)), w)
    doc_key = dsum / np.maximum(cnt, 1.0)
    order = np.arange(seg.ndocs, dtype=np.int64)
    # explicit node stack (depth ~log2(ndocs/leaf)): each entry is a
    # half-open slice of `order` still to bisect
    stack: List[Tuple[int, int, int]] = [(0, seg.ndocs, 0)]
    leaf = max(int(leaf), 2)
    while stack:
        lo, hi, depth = stack.pop()
        n = hi - lo
        if n <= leaf or depth >= REORDER_MAX_DEPTH:
            continue
        node, first_k = _node_passes(order[lo:hi], dstarts, feat, w,
                                     nfeat, passes)
        if depth > 0 and first_k <= max(n // 100, 1):
            # converged (pure cluster): stratify by impact and stop —
            # stable sort on (-key, arrival) keeps determinism
            keys = doc_key[node]
            node = node[np.argsort(-keys, kind="stable")]
            order[lo:hi] = node
            continue
        order[lo:hi] = node
        mid = lo + n // 2
        stack.append((mid, hi, depth + 1))
        stack.append((lo, mid, depth + 1))
    return order


class _PermutedSeq:
    """Lazy permuted view over a list-like (bench segments carry lazy
    _ids/_source sequences a materializing list-comp would defeat)."""

    __slots__ = ("_base", "_order")

    def __init__(self, base, order: np.ndarray):
        self._base = base
        self._order = order

    def __len__(self):
        return len(self._order)

    def __getitem__(self, i):
        return self._base[int(self._order[i])]

    def __iter__(self):
        for i in range(len(self._order)):
            yield self[i]


def _permute_seq(base, order: np.ndarray):
    if base is None:
        return None
    if isinstance(base, list):
        return [base[int(i)] for i in order]
    return _PermutedSeq(base, order)


def _permute_postings(pb: PostingsBlock, old2new: np.ndarray
                      ) -> PostingsBlock:
    """Remap one CSR field and re-sort every row doc-ascending. Past the
    device threshold the (row, doc) two-key sort runs on the TPU
    (ops/device_merge.merge_sorted_runs — the merge pipeline's kernel);
    the host lexsort is the bit-identical fallback."""
    from ..ops import device_merge

    if pb.size == 0:
        return pb
    lens = np.diff(pb.starts)
    rows = np.repeat(np.arange(pb.nterms, dtype=np.int64), lens)
    nd = old2new[pb.doc_ids]
    if device_merge.use_device_merge(pb.size):
        _r, d32, t32, order, _counts = device_merge.merge_sorted_runs(
            rows, nd, pb.tfs, pb.nterms)
        new_docs = d32.astype(np.int32)
        new_tfs = t32.astype(np.float32)
        order = order.astype(np.int64)
    else:
        order = np.lexsort((nd, rows))
        new_docs = nd[order].astype(np.int32)
        new_tfs = pb.tfs[order].astype(np.float32)
    pos_starts = positions = None
    if pb.pos_starts is not None:
        plens = np.diff(pb.pos_starts)[order]
        idx = _ranges_gather(pb.pos_starts[:-1][order], plens)
        positions = pb.positions[idx]
        pos_starts = np.zeros(len(plens) + 1, np.int64)
        np.cumsum(plens, out=pos_starts[1:])
    out = PostingsBlock(pb.field, pb.vocab, pb.terms, pb.starts.copy(),
                        new_docs, new_tfs, pos_starts, positions)
    if pb.impact is not None:
        ip = pb.impact
        # the (tf, dl) multiset per term is permutation-invariant, so the
        # quantized values and the global scale carry over unchanged —
        # only the per-block maxima see the new layout
        q = ip.q[order]
        if len(ip.block_off):
            block_max = np.maximum.reduceat(q, ip.block_off)
        else:
            block_max = np.zeros(0, q.dtype)
        from .segment import ImpactPlane
        out.impact = ImpactPlane(
            q=q, scale=ip.scale, bits=ip.bits, k1=ip.k1, b=ip.b,
            avgdl=ip.avgdl, dl_max=ip.dl_max,
            block_starts=ip.block_starts.copy(),
            block_off=ip.block_off.copy(), block_max=block_max)
    return out


def apply_permutation(seg: Segment, new_order: np.ndarray) -> Segment:
    """Rebuild `seg` with doc ids permuted by `new_order` (new -> old).
    Every per-doc plane — postings, doc values, stored fields, _ids,
    seq_nos, live, nested children — threads through; postings rows stay
    doc-ascending; impact planes are permuted and re-sidecared."""
    ndocs = seg.ndocs
    new_order = np.asarray(new_order, np.int64)
    assert len(new_order) == ndocs
    old2new = np.empty(ndocs, np.int64)
    old2new[new_order] = np.arange(ndocs, dtype=np.int64)

    postings = {f: _permute_postings(pb, old2new)
                for f, pb in seg.postings.items()}
    numeric = {f: NumericColumn(f, col.kind, col.values[new_order],
                                col.present[new_order])
               for f, col in seg.numeric_cols.items()}
    keyword = {}
    for f, col in seg.keyword_cols.items():
        nd = old2new[col.doc_of_value]
        o = np.lexsort((col.ords, nd))
        docs = nd[o].astype(np.int32)
        ords = col.ords[o].astype(np.int32)
        starts = np.zeros(ndocs + 1, np.int64)
        np.cumsum(np.bincount(docs, minlength=ndocs), out=starts[1:])
        keyword[f] = KeywordColumn(f, col.vocab, starts, ords, docs,
                                   col.min_ord[new_order])
    geo = {}
    for f, col in seg.geo_cols.items():
        from .segment import GeoColumn
        geo[f] = GeoColumn(f, col.lat[new_order], col.lon[new_order],
                           col.present[new_order])
    vectors = {}
    for f, col in seg.vector_cols.items():
        from .segment import VectorColumn
        vectors[f] = VectorColumn(f, col.values[new_order],
                                  col.present[new_order], col.similarity,
                                  method=col.method)
    shapes = {}
    for f, col in seg.shape_cols.items():
        from .segment import ShapeColumn
        shapes[f] = ShapeColumn(
            f, [col.specs[int(i)] for i in new_order],
            col.minx[new_order], col.miny[new_order],
            col.maxx[new_order], col.maxy[new_order],
            col.present[new_order])
    doc_lens = {f: dl[new_order] for f, dl in seg.doc_lens.items()}
    nested = {}
    for path, blk in seg.nested.items():
        # children re-sort by NEW parent id so parent_of stays
        # nondecreasing (children_of binary-searches it); the child
        # segment recursively permutes by the same child order
        new_parent = old2new[blk.parent_of]
        corder = np.argsort(new_parent, kind="stable").astype(np.int64)
        child = apply_permutation(blk.child, corder)
        nested[path] = NestedBlock(child,
                                   new_parent[corder].astype(np.int32))

    stored = seg.stored_vals
    # ids/sources attach AFTER construction: Segment.__init__ builds
    # id2doc by iterating the full ids sequence, which would materialize
    # a lazy _PermutedSeq doc-by-doc (1M+ synthesized id strings on the
    # bench corpora this laziness exists for) only to be thrown away below
    out = Segment(seg.name, ndocs, postings, numeric, keyword, geo,
                  doc_lens,
                  {f: st for f, st in seg.text_stats.items()},
                  [], [],
                  seq_nos=seg.seq_nos[new_order],
                  vector_cols=vectors, nested=nested, shape_cols=shapes,
                  stored_vals=_permute_seq(stored, new_order),
                  codec_version=seg.codec_version)
    out.ids = _permute_seq(seg.ids, new_order)
    out.sources = _permute_seq(seg.sources, new_order)
    out.live = seg.live[new_order]
    if isinstance(out.ids, list):
        out.id2doc = {d: i for i, d in enumerate(out.ids)}
    else:
        out.id2doc = {}       # lazy-id corpora (bench) never realtime-get
    tv = getattr(seg, "term_vectors", None)
    if tv:
        out.term_vectors = {f: [col[int(i)] for i in new_order]
                            for f, col in tv.items()}
    derived = seg.__dict__.get("_derived_names")
    if derived:
        out.__dict__["_derived_names"] = set(derived)
    # pin the arrival-rank tie plane explicitly: Segment.tie_ranks infers
    # it from seq_no monotonicity, which degenerates when seq_nos carry
    # no order (direct-CSR corpora default them to zeros — bench
    # make_index) and would silently disable the whole tie-parity
    # machinery on the reordered arm. The source's arrival order is its
    # own tie plane when present, doc order otherwise.
    src_tr = seg.tie_ranks()
    if src_tr is None:
        src_tr = np.arange(seg.ndocs, dtype=np.int64)
    out.__dict__["_tie_rank"] = np.ascontiguousarray(src_tr[new_order])
    # the marker gates tie_ranks() (never-reordered segments must keep
    # their historical internal-id tie order) and the engine's lone-
    # segment forcemerge; maybe_reorder also sets it on no-op passes
    out.__dict__["_reordered"] = True
    return out


def maybe_reorder(seg: Segment) -> Segment:
    """The merge-time entry point: gate, compute, apply. Returns the
    input segment unchanged when the pass is skipped."""
    if not enabled():
        return seg
    if getattr(seg, "codec_version", CODEC_V1) < CODEC_V2:
        return seg
    if seg.ndocs < min_docs():
        return seg
    import time
    t0 = time.perf_counter()
    order = compute_permutation(seg)
    if order is None:
        # pass ran and found nothing to cluster (empty signature band):
        # mark it so engine.force_merge's lone-segment gate doesn't
        # re-run a full single-segment merge on every subsequent call.
        # Doc order was NOT permuted, so pin an absent tie plane too —
        # the marker alone would otherwise let tie_ranks() reconstruct a
        # bogus seq-rank plane on merge-concatenated (non-monotonic
        # seq_no) segments whose historical tie order is the internal id
        seg.__dict__["_reordered"] = True
        seg.__dict__.setdefault("_tie_rank", None)
        return seg
    out = apply_permutation(seg, order)
    out.__dict__["_reordered"] = True
    dt_ms = (time.perf_counter() - t0) * 1e3
    if METRICS.enabled:
        METRICS.counter("reorder.segments").inc()
        METRICS.histogram("reorder.wall_ms").record(dt_ms)
    return out
