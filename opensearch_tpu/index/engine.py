"""The index engine: buffered writes, realtime get, refresh, flush/commit,
recovery. Analog of reference `index/engine/InternalEngine.java` +
`index/shard/IndexShard.java`.

Write path: parse → version/concurrency check → translog append → in-memory
buffer. `refresh()` turns the buffer into an immutable device-resident
Segment (the searchable unit). `flush()` persists segments + a commit point
and rolls the translog. Opening an engine on an existing path recovers from
the last commit point + translog replay (reference:
InternalEngine#recoverFromTranslog).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..obs import ingest_obs as _iobs
from ..utils.metrics import METRICS
from .mappings import Mappings, ParsedDocument
from .merge import TieredMergePolicy, merge_segments
from .segment import (Segment, build_segment, build_segment_streaming,
                      stream_eligible)
from .translog import Translog

# refresh buffers at or past this many docs take the streaming builder
# (chunked pack + disk spill-and-merge, index/segment.py
# StreamingSegmentBuilder) — the in-memory pack's transient Python token
# buffers dominate host memory well before the final CSR does. Output is
# bit-identical either way, so the threshold is purely a memory knob.
STREAM_REFRESH_MIN_DOCS = 1 << 16


def stream_refresh_min_docs() -> int:
    return int(os.environ.get("OPENSEARCH_TPU_STREAM_REFRESH_DOCS",
                              STREAM_REFRESH_MIN_DOCS))


class VersionConflictError(Exception):
    """Analog of reference VersionConflictEngineException (HTTP 409)."""


@dataclass
class DocLocation:
    seq_no: int
    in_buffer: bool
    segment: Optional[Segment] = None
    local_doc: int = -1
    buffer_idx: int = -1


class Engine:
    def __init__(self, mappings: Mappings, path: Optional[str] = None,
                 merge_policy: Optional[TieredMergePolicy] = None,
                 primary_term: int = 1):
        self.mappings = mappings
        self.path = path
        self.merge_policy = merge_policy or TieredMergePolicy()
        self.primary_term = primary_term
        self.index_name = ""       # set by IndexService; labels per-index obs
        self.segments: List[Segment] = []
        self.buffer: List[ParsedDocument] = []
        self.buffer_seq: List[int] = []
        # accept-time monotonic stamp per buffered doc (parallel to
        # `buffer`; survives tombstoning) — refresh publishes the
        # accept→searchable delta as `indexing.refresh_to_visible_ms`
        self.buffer_accepts: List[float] = []
        # what THIS engine contributed to the process buffer gauges —
        # refresh subtracts exactly this, so enable toggles mid-buffer
        # never skew the totals
        self._obs_buf_docs = 0
        self._obs_buf_bytes = 0
        # accepted docs not yet folded into the process gauges/counters
        # (amortized every ingest_obs.FLUSH_EVERY docs and at refresh)
        self._obs_pend_docs = 0
        self._buffer_ids: Dict[str, int] = {}
        self.seq_no = -1
        self._seg_counter = 0
        self.version_map: Dict[str, DocLocation] = {}
        self._tombstones: Dict[str, int] = {}
        self.translog: Optional[Translog] = None
        self.last_commit_gen = 0
        self.stats = {"index_ops": 0, "delete_ops": 0, "refreshes": 0,
                      "flushes": 0, "merges": 0}
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._recover()

    # ---------------- write path ----------------

    def _next_seq(self) -> int:
        self.seq_no += 1
        return self.seq_no

    def _check_concurrency(self, doc_id: str, if_seq_no: Optional[int],
                           if_primary_term: Optional[int], op: str) -> None:
        if if_seq_no is None and if_primary_term is None:
            return
        loc = self.version_map.get(doc_id)
        cur = loc.seq_no if loc else -1
        if if_seq_no is not None and cur != if_seq_no:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, required seqNo [{if_seq_no}], "
                f"current document has seqNo [{cur}] ({op})")
        if if_primary_term is not None and self.primary_term != if_primary_term:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict on primary term ({op})")

    def index_doc(self, doc_id: str, source: dict, routing: Optional[str] = None,
                  if_seq_no: Optional[int] = None, if_primary_term: Optional[int] = None,
                  op_type: str = "index", translog_op: bool = True) -> dict:
        self._check_concurrency(doc_id, if_seq_no, if_primary_term, "index")
        existed = doc_id in self.version_map
        if op_type == "create" and existed:
            raise VersionConflictError(f"[{doc_id}]: document already exists")
        parsed = self.mappings.parse(doc_id, source, routing)
        seq = self._next_seq()
        if translog_op and self.translog is not None:
            self.translog.add_index(doc_id, source, routing, seq)
        self._delete_previous(doc_id)
        self._buffer_ids[doc_id] = len(self.buffer)
        self.buffer.append(parsed)
        self.buffer_seq.append(seq)
        self.buffer_accepts.append(time.monotonic())
        self.version_map[doc_id] = DocLocation(seq, in_buffer=True,
                                               buffer_idx=len(self.buffer) - 1)
        self._tombstones.pop(doc_id, None)
        self.stats["index_ops"] += 1
        if _iobs.enabled():
            # ONE int add — this runs under the index write lock on every
            # accepted doc; byte sizing and registry emission are
            # amortized via _obs_flush_pending (every FLUSH_EVERY docs +
            # at refresh). Anything heavier here is a measurable bulk
            # throughput hit at 32 submit threads.
            self._obs_pend_docs += 1
            if self._obs_pend_docs >= _iobs.FLUSH_EVERY:
                self._obs_flush_pending()
        return {"_id": doc_id, "_seq_no": seq, "_primary_term": self.primary_term,
                "result": "updated" if existed else "created"}

    def delete_doc(self, doc_id: str, if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None, translog_op: bool = True) -> dict:
        self._check_concurrency(doc_id, if_seq_no, if_primary_term, "delete")
        found = doc_id in self.version_map
        seq = self._next_seq()
        if translog_op and self.translog is not None:
            self.translog.add_delete(doc_id, seq)
        if found:
            self._delete_previous(doc_id)
            del self.version_map[doc_id]
            self._tombstones[doc_id] = seq
        self.stats["delete_ops"] += 1
        if _iobs.enabled():
            METRICS.counter("indexing.docs.deleted").inc()
        return {"_id": doc_id, "_seq_no": seq, "_primary_term": self.primary_term,
                "result": "deleted" if found else "not_found"}

    def _delete_previous(self, doc_id: str) -> None:
        loc = self.version_map.get(doc_id)
        if loc is None:
            return
        if loc.in_buffer:
            idx = self._buffer_ids.pop(doc_id, None)
            if idx is not None:
                # tombstone the buffered doc (compacted away at refresh)
                self.buffer[idx] = None
        else:
            loc.segment.delete_doc(loc.local_doc)

    # ---------------- realtime get ----------------

    def get(self, doc_id: str) -> Optional[dict]:
        """Realtime get through the version map (reference: InternalEngine#get
        refreshes-on-demand; our buffer is directly readable so no refresh)."""
        loc = self.version_map.get(doc_id)
        if loc is None:
            return None
        if loc.in_buffer:
            parsed = self.buffer[loc.buffer_idx]
            return {"_id": doc_id, "_source": parsed.source, "_seq_no": loc.seq_no,
                    "_primary_term": self.primary_term, "found": True}
        return {"_id": doc_id, "_source": loc.segment.sources[loc.local_doc],
                "_seq_no": loc.seq_no, "_primary_term": self.primary_term, "found": True}

    # ---------------- refresh / merge / flush ----------------

    @property
    def num_docs(self) -> int:
        return sum(s.live_count for s in self.segments) + \
            sum(1 for d in self.buffer if d is not None)

    def refresh(self) -> bool:
        # Stage boundaries t0..t4 partition the refresh wall time EXACTLY
        # (stage_i = t_{i+1} - t_i, so collect+build+publish+merge equals
        # the total by construction — tests/test_ingest_obs.py pins it).
        # Stamps are unconditional (4 perf_counter reads per refresh);
        # everything else is gated on the ingest-obs flag.
        t0 = time.perf_counter()
        obs_on = _iobs.enabled()
        self._obs_flush_pending()
        live_docs = [(d, s, a) for d, s, a in
                     zip(self.buffer, self.buffer_seq, self.buffer_accepts)
                     if d is not None]
        self.buffer = []
        self.buffer_seq = []
        self.buffer_accepts = []
        self._buffer_ids = {}
        if self._obs_buf_docs or self._obs_buf_bytes:
            _iobs.buffer_delta(-self._obs_buf_docs, -self._obs_buf_bytes)
            self._obs_buf_docs = 0
            self._obs_buf_bytes = 0
        if not live_docs:
            return False
        docs = [d for d, _, _ in live_docs]
        seqs = [s for _, s, _ in live_docs]
        accepts = [a for _, _, a in live_docs]
        name = f"_{self._seg_counter}"
        self._seg_counter += 1
        t1 = time.perf_counter()
        streamed = False
        with _iobs.stage_scope() as build_detail:
            if len(docs) >= stream_refresh_min_docs() and stream_eligible(docs):
                seg = build_segment_streaming(name, docs, self.mappings,
                                              seq_nos=seqs,
                                              spill_dir=(os.path.join(
                                                  self.path, "_stream_spill")
                                                  if self.path else None))
                self.stats["stream_refreshes"] = \
                    self.stats.get("stream_refreshes", 0) + 1
                streamed = True
            else:
                seg = build_segment(name, docs, self.mappings, seq_nos=seqs)
        t2 = time.perf_counter()
        self.segments.append(seg)
        for local, d in enumerate(docs):
            self.version_map[d.doc_id] = DocLocation(
                seqs[local], in_buffer=False, segment=seg, local_doc=local)
        self.stats["refreshes"] += 1
        t3 = time.perf_counter()
        # the docs became searchable at publish (t3): record the honest
        # accept→visible delta BEFORE the piggybacked merge work
        if obs_on:
            _iobs.record_refresh_to_visible(self.index_name, accepts,
                                            time.monotonic())
        self.maybe_merge()
        t4 = time.perf_counter()
        if obs_on:
            _iobs.record_refresh(self.index_name, len(docs), streamed,
                                 (t0, t1, t2, t3, t4), build_detail,
                                 self.merge_backlog())
        return True

    def maybe_merge(self) -> None:
        for group in self.merge_policy.find_merges(self.segments):
            if len(group) < 2 and not any(s.live_count < s.ndocs for s in group):
                continue
            self.force_merge_group(group)

    def _obs_flush_pending(self) -> None:
        """Fold the accepted docs since the last fold into the process
        buffer gauges and the indexed counter (amortization contract:
        ingest_obs.FLUSH_EVERY). Bytes are a sampled structural
        estimate: size at most BYTES_SAMPLE docs from the buffer tail
        (the ones this fold covers) and scale to the fold — the gauge
        is an estimate by contract, and sizing every doc is a measured
        bulk-throughput hit. Must run before the buffer is cleared."""
        n = self._obs_pend_docs
        if not n:
            return
        tail = self.buffer[-n:]
        samples = [p for p in tail[::max(1, n // _iobs.BYTES_SAMPLE)]
                   if p is not None][:_iobs.BYTES_SAMPLE]
        est = (int(sum(_iobs.doc_bytes(p.source) for p in samples)
                   / len(samples) * n) if samples else 0)
        self._obs_buf_docs += n
        self._obs_buf_bytes += est
        self._obs_pend_docs = 0
        _iobs.buffer_delta(n, est)
        METRICS.counter("indexing.docs.indexed").inc(n)

    def merge_backlog(self) -> int:
        """Merge groups the policy would run right now — this engine's
        slice of the `indexing.merge.backlog` write-pressure gauge (0
        right after `maybe_merge` unless max_merged_docs defers work)."""
        return len([g for g in self.merge_policy.find_merges(self.segments)
                    if len(g) >= 2
                    or any(s.live_count < s.ndocs for s in g)])

    def buffer_stats(self) -> dict:
        """Live writer-buffer shape (docs pending refresh + tracked byte
        estimate) for `_stats` / `_cat/indices`."""
        return {"docs": sum(1 for d in self.buffer if d is not None),
                "bytes": self._obs_buf_bytes}

    def force_merge_group(self, group: List[Segment]) -> Segment:
        name = f"_m{self._seg_counter}"
        self._seg_counter += 1
        merged = merge_segments(name, group)
        group_set = set(id(s) for s in group)
        self.segments = [s for s in self.segments if id(s) not in group_set]
        self.segments.append(merged)
        for local, doc_id in enumerate(merged.ids):
            loc = self.version_map.get(doc_id)
            if loc is not None and not loc.in_buffer:
                self.version_map[doc_id] = DocLocation(
                    int(merged.seq_nos[local]), in_buffer=False,
                    segment=merged, local_doc=local)
        self.stats["merges"] += 1
        return merged

    def force_merge(self, max_num_segments: int = 1) -> None:
        if len(self.segments) > max_num_segments:
            self.force_merge_group(list(self.segments))
            return
        # a lone codec-v2 segment still takes the merge-time BP reorder
        # pass (index/reorder.py): forcemerge is the "optimize layout"
        # call, and whether the corpus arrived in one refresh or ten must
        # not decide whether the pass ran. Gated on the pass actually
        # being applicable so small/v1/already-reordered segments keep
        # the historical no-op.
        from . import reorder
        from .segment import CODEC_V2
        if (len(self.segments) == 1 and reorder.enabled()
                and getattr(self.segments[0], "codec_version", 1)
                >= CODEC_V2
                and not self.segments[0].__dict__.get("_reordered")
                and self.segments[0].ndocs >= reorder.min_docs()):
            self.force_merge_group(list(self.segments))

    def flush(self) -> None:
        """Durable commit: segments to disk + commit point, translog rolled
        (reference: InternalEngine#flush -> Lucene commit + translog trim)."""
        t0 = time.perf_counter()
        self.refresh()
        if self.path is None:
            return
        seg_dir = os.path.join(self.path, "segments")
        committed = []
        for seg in self.segments:
            d = os.path.join(seg_dir, seg.name)
            if not os.path.exists(os.path.join(d, "meta.json")):
                seg.save(d)
            else:
                # persist up-to-date live masks for previously saved segments
                import numpy as np
                seg.save(d)
            committed.append(seg.name)
        # translog age at commit = how stale durability was just before
        # this flush (measured BEFORE rollover resets the generation)
        tl_age = self.translog.age_s() if self.translog else 0.0
        gen = self.translog.rollover() if self.translog else 0
        commit = {"segments": committed, "seq_no": self.seq_no,
                  "translog_gen": gen, "primary_term": self.primary_term,
                  "ts": time.time()}
        tmp = os.path.join(self.path, "commit.json.tmp")
        with open(tmp, "w") as fh:
            json.dump(commit, fh)
        os.replace(tmp, os.path.join(self.path, "commit.json"))
        if self.translog:
            self.translog.prune_below(gen)
        self.last_commit_gen = gen
        self.stats["flushes"] += 1
        if _iobs.enabled():
            _iobs.record_flush((time.perf_counter() - t0) * 1000.0, tl_age)

    # ---------------- recovery ----------------

    def _recover(self) -> None:
        commit_path = os.path.join(self.path, "commit.json")
        translog_dir = os.path.join(self.path, "translog")
        gen = 0
        if os.path.exists(commit_path):
            with open(commit_path) as fh:
                commit = json.load(fh)
            for name in commit["segments"]:
                seg = Segment.load(os.path.join(self.path, "segments", name))
                self.segments.append(seg)
                num = int(name.lstrip("_m").lstrip("_") or 0)
                self._seg_counter = max(self._seg_counter, num + 1)
                for local, doc_id in enumerate(seg.ids):
                    if seg.live[local]:
                        self.version_map[doc_id] = DocLocation(
                            int(seg.seq_nos[local]), in_buffer=False,
                            segment=seg, local_doc=local)
            self.seq_no = commit["seq_no"]
            gen = commit["translog_gen"]
            self.primary_term = commit.get("primary_term", 1)
        self.translog = Translog(translog_dir, generation=gen)
        replayed = 0
        for rec in self.translog.replay_from(gen):
            if rec["seq_no"] <= self.seq_no and os.path.exists(commit_path):
                continue
            if rec["op"] == "index":
                self.index_doc(rec["_id"], rec["_source"], rec.get("routing"),
                               translog_op=False)
            else:
                self.delete_doc(rec["_id"], translog_op=False)
            replayed += 1
        if replayed:
            self.refresh()

    # ---------------- index-wide stats for scoring ----------------

    def codec_mix(self) -> Dict[int, int]:
        """Live segments per codec version — the serving tier can carry a
        mixed v1/v2 set indefinitely (v1 loads untouched; refresh/merge
        emit the process default). Surfaced in bench `extra.impacts` and
        scripts/hbm_report.py."""
        mix: Dict[int, int] = {}
        for s in self.segments:
            v = int(getattr(s, "codec_version", 1))
            mix[v] = mix.get(v, 0) + 1
        return mix

    def field_stats(self, field: str):
        """Index-wide (doc_count, sum_dl, total_docs) for BM25 avgdl/idf —
        the analog of Lucene CollectionStatistics aggregated across leaves."""
        doc_count = 0
        sum_dl = 0
        for s in self.segments:
            st = s.text_stats.get(field)
            if st:
                doc_count += st.doc_count
                sum_dl += st.sum_dl
        return doc_count, sum_dl

    def doc_freq(self, field: str, term: str) -> int:
        return sum(s.postings[field].doc_freq(term)
                   for s in self.segments if field in s.postings)

    def close(self) -> None:
        if self.translog:
            self.translog.close()
