"""Immutable index segments: CSR posting blocks + columnar doc values in HBM.

This replaces Lucene's segment files (reference: `index/codec/`, Lucene
Lucene101PostingsFormat / DocValuesFormat / StoredFieldsFormat). Layout is
TPU-first instead of disk-first:

- Postings for one field are a CSR matrix over (term row -> doc postings):
  `starts[t]..starts[t+1]` indexes flat `doc_ids` / `tfs` arrays. Flat arrays
  are padded to power-of-two lengths so XLA sees a small set of static shapes
  across segments (compile-cache friendly); padded doc_ids hold an
  out-of-range sentinel so scatter `mode=drop` ignores them.
- Term frequencies are stored as f32 (exact for tf < 2^24) so the BM25
  tf-saturation runs on the VPU with no decode step — the analog of Lucene's
  "impacts" but kept separate from the per-doc length norm so k1/b/avgdl stay
  query-time parameters (similarity parity with reference
  `index/similarity/`).
- Doc values are dense columns: the long family (long/date/boolean/ip-lo...)
  is stored as exact (hi,lo) i32 pairs (TPU jit default is 32-bit; the pair
  compare keeps 64-bit range semantics exact), floats as f32, keywords as a
  doc-major CSR of segment-local ordinals + per-doc min-ord for sorting.
- Stored fields (`_source`) stay on host (the device never needs them; the
  fetch phase is host-side, reference `search/fetch/FetchPhase.java`).
"""

from __future__ import annotations

import json
import math
import os
import time
from dataclasses import dataclass, field as dc_field
from functools import cached_property
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.ingest_obs import note_stage
from .mappings import FLOAT_TYPES, GEO_TYPES, FieldType, Mappings

INT32_SENTINEL = np.int32(2**31 - 1)  # padded doc_id -> dropped by scatter

# ---------------------------------------------------------------------
# segment codec versions (docs/INDEX_FORMAT.md)
# ---------------------------------------------------------------------
#
# v1: CSR postings carry (doc_id i32, tf f32); every query re-derives the
#     BM25 tf-saturation from tf + the doc-length column on the device.
# v2: additionally carries a per-field *impact plane*: the BM25
#     tf-saturation tf/(tf + k1·(1-b+b·dl/avgdl)) pre-evaluated at build
#     time under nominal similarity params and quantized to u8/u16 with
#     ONE global per-field scale (BM25S-style eager scoring, arxiv
#     2407.03618), plus a per-128-posting block-max sidecar enabling
#     MaxScore/block-max pruning (GPUSparse, arxiv 2606.26441). The query
#     hot path becomes gather -> scatter-add over integer impacts with no
#     per-query tf/doclen math (search/impactpath.py); exactness vs the
#     f32 oracle is re-established by a certify-or-escalate ladder whose
#     margin folds in the quantization error (ImpactPlane.quant_err).
#     v1 segments still load and serve — the codec is version-gated
#     everywhere (oslint OSL507: consult Segment.codec_version).
CODEC_V1 = 1
CODEC_V2 = 2
IMPACT_BLOCK = 128        # postings per block-max sidecar entry
IMPACT_K1 = 1.2           # nominal build-time similarity params; query-time
IMPACT_B = 0.75           # drift is bounded by ImpactPlane.drift_bound


def default_codec_version() -> int:
    """Codec for NEW segments (refresh/merge). OPENSEARCH_TPU_CODEC=1
    pins the legacy tf-only format (compat tests, rollback)."""
    return CODEC_V1 if os.environ.get("OPENSEARCH_TPU_CODEC") == "1" \
        else CODEC_V2


def default_impact_bits() -> int:
    """Impact quantization width: 16 (default, error ~scale/2^17) or 8
    via OPENSEARCH_TPU_IMPACT_BITS=8 (half the plane bytes; the wider
    error folds into the same serve margin)."""
    return 8 if os.environ.get("OPENSEARCH_TPU_IMPACT_BITS") == "8" else 16


@dataclass
class ImpactPlane:
    """Quantized eager BM25 impacts for one field's CSR postings (codec
    v2). `q[i]` dequantizes through the designated helpers
    (ops/scoring.py `dequant_impact`/`dequant_impact_np`, oslint OSL507)
    to `q[i] * scale` ~= tf_i/(tf_i + k1·(1-b+b·dl_i/avgdl)) evaluated at
    the BUILD-time nominal (k1, b, avgdl). The block sidecar stores, per
    IMPACT_BLOCK-posting run of each row, the max quantized impact — an
    exact upper bound in the quantized domain, so host/device pruning
    decisions against it carry no extra error term."""

    q: np.ndarray             # u8/u16[P] quantized impacts, CSR-flat
    scale: float              # dequant scale: impact ~= q * scale
    bits: int                 # 8 | 16
    k1: float                 # build-time nominal similarity params
    b: float
    avgdl: float
    dl_max: int               # max doc length seen (drift bound input)
    block_starts: np.ndarray  # i64[nterms+1] block-CSR row pointers
    block_off: np.ndarray     # i64[nblocks] flat element start per block
    block_max: np.ndarray     # u8/u16[nblocks] max q per block
    # "bm25": q dequantizes to the BM25 tf-saturation under the baked
    #   nominal (k1, b, avgdl) — query-time drift priced by drift_bound.
    # "feature": q dequantizes DIRECTLY to the model-assigned feature
    #   weight of a rank_features/sparse_vector posting (opt-in
    #   `index_impacts` mapping param) — weights are query-independent,
    #   so the only serve error is the quantization half-step
    #   (quant_err); drift_bound must never be consulted.
    kind: str = "bm25"

    @property
    def qmax(self) -> int:
        return (1 << self.bits) - 1

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes + self.block_max.nbytes
                   + self.block_off.nbytes + self.block_starts.nbytes)

    def quant_err(self) -> float:
        """Sound per-posting |exact f32 impact − q·scale| bound at the
        BUILD params: half a quantization step plus f32 slack for the
        dequant multiply."""
        top = np.float32(self.scale) * np.float32(self.qmax)
        return float(self.scale) * 0.5 + 2.0 * float(np.spacing(top))

    def drift_bound(self, k1q: float, bq: float, avgdlq: float) -> float:
        """Sound bound on |f_query − f_build| per posting when query-time
        (k1, b, avgdl) differ from the baked build params: with
        k(dl) = k1·(1-b+b·dl/avgdl) linear in dl, Δk is maximized at a dl
        endpoint, and tf/((tf+ka)(tf+kb)) ≤ 1/(√ka+√kb)² (or its tf=1
        value when the unconstrained max lies below tf=1)."""
        if (float(k1q) == float(self.k1) and float(bq) == float(self.b)
                and float(avgdlq) == float(self.avgdl)):
            return 0.0

        def k_of(dl, k1, b, avg):
            return k1 * (1.0 - b + b * dl / max(avg, 1e-9))

        dk = max(abs(k_of(0.0, k1q, bq, avgdlq)
                     - k_of(0.0, self.k1, self.b, self.avgdl)),
                 abs(k_of(float(self.dl_max), k1q, bq, avgdlq)
                     - k_of(float(self.dl_max), self.k1, self.b,
                            self.avgdl)))
        ka = max(k_of(0.0, k1q, bq, avgdlq), 0.0)
        kb = max(k_of(0.0, self.k1, self.b, self.avgdl), 0.0)
        if ka * kb >= 1.0:
            g = 1.0 / (math.sqrt(ka) + math.sqrt(kb)) ** 2
        else:
            g = 1.0 / ((1.0 + ka) * (1.0 + kb))
        return min(dk * g, 1.0)

    def row_block_range(self, row: int) -> Tuple[int, int]:
        return int(self.block_starts[row]), int(self.block_starts[row + 1])


def build_impact_plane(pb: "PostingsBlock", dl: Optional[np.ndarray],
                       avgdl: Optional[float] = None,
                       bits: Optional[int] = None) -> Optional[ImpactPlane]:
    """Quantize one field's eager impacts + block-max sidecar (the codec
    v2 build step, shared by refresh, merge and direct corpus wrappers).
    The f32 expression mirrors the host oracle's per-posting arithmetic
    (search/fastpath.py `_exact_rescore`) so the quantization-error bound
    is measured against the exact serve domain."""
    if pb.size == 0:
        return None
    bits = default_impact_bits() if bits is None else int(bits)
    tfs = pb.tfs.astype(np.float32)
    if dl is not None:
        dl_of = dl[pb.doc_ids].astype(np.float32)
        dl_max = int(dl.max()) if len(dl) else 0
    else:
        dl_of = np.zeros(pb.size, np.float32)
        dl_max = 0
    if avgdl is None:
        pos = dl_of[dl_of > 0]
        avgdl = float(pos.mean()) if len(pos) else 1.0
    avgdl = max(float(avgdl), 1e-9)
    from ..ops.device_merge import quantize_impacts, use_device_impacts
    qmax = (1 << bits) - 1
    if use_device_impacts(pb.size):
        q32, scale = quantize_impacts(tfs, dl_of, IMPACT_K1, IMPACT_B,
                                      avgdl, qmax)
        q = q32.astype(np.uint8 if bits == 8 else np.uint16)
    else:
        kfac = IMPACT_K1 * (1.0 - IMPACT_B + IMPACT_B * dl_of / avgdl)
        imp = tfs / (tfs + kfac)
        m = float(imp.max()) if len(imp) else 0.0
        scale = (m / qmax) if m > 0 else 1.0
        q = np.minimum(np.round(imp / np.float32(scale)), qmax).astype(
            np.uint8 if bits == 8 else np.uint16)
    block_starts, block_off, block_max = _impact_sidecar(pb, q)
    return ImpactPlane(q=q, scale=float(scale), bits=bits,
                       k1=IMPACT_K1, b=IMPACT_B, avgdl=float(avgdl),
                       dl_max=dl_max, block_starts=block_starts,
                       block_off=block_off, block_max=block_max)


def _impact_sidecar(pb: "PostingsBlock", q: np.ndarray):
    """Per-IMPACT_BLOCK-posting block-max sidecar over one quantized
    plane: (block_starts i64[nterms+1], block_off i64[nblocks],
    block_max u8/u16[nblocks])."""
    lens = np.diff(pb.starts)
    nblk = -(-lens // IMPACT_BLOCK)           # ceil; empty rows -> 0 blocks
    block_starts = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(nblk, out=block_starts[1:])
    nblocks = int(block_starts[-1])
    if nblocks:
        # flat element offset of each block: row start + j*IMPACT_BLOCK
        row_of_blk = np.repeat(np.arange(len(lens), dtype=np.int64), nblk)
        j = np.arange(nblocks, dtype=np.int64) - block_starts[row_of_blk]
        block_off = pb.starts[row_of_blk].astype(np.int64) \
            + j * IMPACT_BLOCK
        block_max = np.maximum.reduceat(q, block_off)
    else:
        block_off = np.zeros(0, np.int64)
        block_max = np.zeros(0, q.dtype)
    return block_starts, block_off, block_max


def build_feature_impact_plane(pb: "PostingsBlock",
                               bits: Optional[int] = None
                               ) -> Optional[ImpactPlane]:
    """Quantize one rank_features/sparse_vector field's model-assigned
    weights into a codec-v2 impact plane (`kind="feature"`, opt-in via
    the `index_impacts` mapping param). The CSR "tf" slot of a feature
    field IS the weight, so the plane stores round(w / scale) with one
    global scale — the learned-sparse dot product then serves through
    the SAME block-max prune → integer gather → certify-or-escalate
    ladder as BM25 impacts (GPUSparse, arxiv 2606.26441), with
    quantization as the only error source (no similarity-param drift:
    weights are query-independent). Mapping-level validation guarantees
    positive weights; a degenerate all-zero plane declines."""
    if pb.size == 0:
        return None
    bits = default_impact_bits() if bits is None else int(bits)
    qmax = (1 << bits) - 1
    w = pb.tfs.astype(np.float32)
    m = float(w.max()) if len(w) else 0.0
    if m <= 0.0:
        return None
    scale = m / qmax
    q = np.minimum(np.round(w / np.float32(scale)), qmax).astype(
        np.uint8 if bits == 8 else np.uint16)
    block_starts, block_off, block_max = _impact_sidecar(pb, q)
    return ImpactPlane(q=q, scale=float(scale), bits=bits,
                       k1=0.0, b=0.0, avgdl=1.0, dl_max=0,
                       block_starts=block_starts, block_off=block_off,
                       block_max=block_max, kind="feature")

# memory accounting for the per-segment DEVICE column cache
# (`device_arrays` HBM residency) goes through the HBM ledger
# (obs/hbm_ledger.py), the single source of truth for device memory: the
# Node wires its fielddata breaker into the LEDGER and every residency
# build registers an attributed allocation there — the breaker charge is
# derived from the registration (oslint OSL506). Charged once per
# (segment, device) pytree build, released by a weakref finalizer when
# the segment is GC'd (segments are immutable and replaced wholesale on
# refresh/merge) or eagerly by `drop_device`.


def set_breaker(breaker) -> None:
    """Legacy wiring shim: the breaker now lives on the ledger."""
    from ..obs.hbm_ledger import LEDGER
    LEDGER.set_breaker(breaker)


def _tree_nbytes(tree) -> int:
    """Total array bytes of a (nested dict of) arrays pytree."""
    if isinstance(tree, dict):
        return sum(_tree_nbytes(v) for v in tree.values())
    return int(getattr(tree, "nbytes", 0))


def next_pow2(n: int, floor: int = 16) -> int:
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


class _BuildLock:
    """Reentrant per-segment build lock that also exposes its hold depth.
    Pressure eviction (`Segment.evict_device`) must refuse a segment
    whose build is in flight, but the evictor frequently runs ON the
    builder's own thread (ledger register -> `_evict_lru` -> evictor,
    all inside a build's critical section) — a bare RLock's reentrant
    acquire would succeed there and let a mid-build plane be dropped.
    The depth counter is only mutated while the lock is held, so reading
    `depth > 1` after a successful acquire is exact.

    The static concurrency pass models this wrapper as a reentrant lock
    kind ("BuildLock"), so the build path's re-entry is exempt from the
    OSL701 self-deadlock rule while its nesting over the HBM ledger
    stays a committed edge in lock_order.json."""

    __slots__ = ("_lock", "depth")

    def __init__(self) -> None:
        import threading
        self._lock = threading.RLock()
        self.depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self.depth += 1
        return ok

    def release(self) -> None:
        self.depth -= 1
        self._lock.release()

    def __enter__(self) -> "_BuildLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _DevicePut:
    """jnp stand-in whose asarray lands on a specific device (replica
    re-hosting path in Segment.device_arrays)."""

    def __init__(self, device):
        self.device = device

    def asarray(self, x):
        import jax
        # transfer helper: every caller (device_arrays/pruned_arrays
        # builds) registers the residency with the ledger
        return jax.device_put(np.asarray(x), self.device)  # oslint: disable=OSL506


def _pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def split_i64(vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """i64 -> (hi i32, lo i32-with-offset-binary) such that lexicographic
    (hi, lo) compare == signed 64-bit compare. lo is biased by 2^31 so a plain
    signed compare works on the low word."""
    v = vals.astype(np.int64)
    hi = (v >> 32).astype(np.int32)
    lo = ((v & 0xFFFFFFFF) - (1 << 31)).astype(np.int64).astype(np.int32)
    return hi, lo


@dataclass
class PostingsBlock:
    """CSR postings for one indexed field."""

    field: str
    vocab: List[str]                    # row -> term (sorted)
    terms: Dict[str, int]               # term -> row
    starts: np.ndarray                  # i64[nterms+1] host row pointers
    doc_ids: np.ndarray                 # i32[P] host
    tfs: np.ndarray                     # f32[P] host
    # optional positional data: pos_starts aligned with postings flat index
    pos_starts: Optional[np.ndarray] = None   # i64[P+1]
    positions: Optional[np.ndarray] = None    # i32[total_positions]
    # codec v2: quantized eager impacts + block-max sidecar (None on v1
    # segments and non-text planes — consumers must version-gate)
    impact: Optional[ImpactPlane] = None

    @property
    def nterms(self) -> int:
        return len(self.vocab)

    @property
    def size(self) -> int:
        return int(self.starts[-1])

    def row(self, term: str) -> int:
        """Row for term, or -1 when absent (maps to the guaranteed-empty
        padding row on device)."""
        return self.terms.get(term, -1)

    def doc_freq(self, term: str) -> int:
        r = self.terms.get(term)
        if r is None:
            return 0
        return int(self.starts[r + 1] - self.starts[r])

    def row_slice(self, row: int) -> Tuple[int, int]:
        return int(self.starts[row]), int(self.starts[row + 1])


@dataclass
class NumericColumn:
    field: str
    kind: str                 # "int" (long family, exact i64) | "float"
    values: np.ndarray        # host i64 or f64
    present: np.ndarray       # bool[ndocs]

    _sort_ords: Optional[np.ndarray] = None

    @property
    def min_max(self) -> Tuple[float, float]:
        if not self.present.any():
            return (0.0, 0.0)
        vals = self.values[self.present]
        return (float(vals.min()), float(vals.max()))

    def sort_ords(self) -> np.ndarray:
        """Per-doc rank of the value among the segment's distinct values —
        exact i32 sort keys for device top-k even when values need 64 bits
        (see SURVEY §2.5 sort). Missing docs get rank -1."""
        if self._sort_ords is None:
            ords = np.full(len(self.values), -1, dtype=np.int32)
            if self.present.any():
                uniq = np.unique(self.values[self.present])
                ords[self.present] = np.searchsorted(uniq, self.values[self.present]).astype(np.int32)
            self._sort_ords = ords
        return self._sort_ords


@dataclass
class KeywordColumn:
    field: str
    vocab: List[str]          # sorted distinct values
    starts: np.ndarray        # i64[ndocs+1] doc-major CSR
    ords: np.ndarray          # i32[total_values]
    doc_of_value: np.ndarray  # i32[total_values] (doc id per flat value)
    min_ord: np.ndarray       # i32[ndocs], -1 = missing

    @property
    def present(self) -> np.ndarray:
        return self.min_ord >= 0


@dataclass
class GeoColumn:
    field: str
    lat: np.ndarray           # f32[ndocs]
    lon: np.ndarray           # f32[ndocs]
    present: np.ndarray


@dataclass
class ShapeColumn:
    """geo_shape storage: host-resident shape specs + per-doc bbox columns.

    The TPU split (vs the reference's Lucene BKD tesselation,
    `index/mapper/GeoShapeFieldMapper.java`): bboxes give a vectorized
    numpy prefilter; exact relations (search/geo.py) run on the host over
    bbox survivors at plan-prepare time; the result is a per-(segment,
    query) boolean mask uploaded as a plan param — static device shapes,
    and the mask rides the (segment, plan) filter cache."""

    field: str
    specs: list                    # per-doc: list of GeoJSON/WKT specs or None
    minx: np.ndarray               # f64[ndocs] bbox columns
    miny: np.ndarray
    maxx: np.ndarray
    maxy: np.ndarray
    present: np.ndarray            # bool[ndocs]
    _parsed: Any = None            # lazy per-doc merged Shape cache

    def shape(self, doc: int):
        """Merged Shape for one doc (multiple values = one collection)."""
        from ..search.geo import Shape, parse_shape
        if self._parsed is None:
            self._parsed = [None] * len(self.specs)
        s = self._parsed[doc]
        if s is None and self.specs[doc]:
            parts = [parse_shape(sp) for sp in self.specs[doc]]
            if len(parts) == 1:
                s = parts[0]
            else:
                s = Shape()
                s.points = np.concatenate([p.points for p in parts])
                for p in parts:
                    s.lines += p.lines
                    s.polys += p.polys
                s.finish()
            self._parsed[doc] = s
        return s

    def bbox_candidates(self, qbbox) -> np.ndarray:
        """bool[ndocs]: docs whose bbox overlaps the query bbox."""
        qminx, qminy, qmaxx, qmaxy = qbbox
        return (self.present & (self.minx <= qmaxx) & (self.maxx >= qminx)
                & (self.miny <= qmaxy) & (self.maxy >= qminy))


@dataclass
class VectorColumn:
    """Dense vectors for kNN search, row-major [ndocs, dims] (brute-force
    exact kNN runs as one MXU matmul per segment — see ops/knn; the
    reference's k-NN plugin uses HNSW/faiss, approximate)."""

    field: str
    values: np.ndarray        # f32[ndocs, dims]
    present: np.ndarray       # bool[ndocs]
    similarity: str = "cosine"
    # ANN method from the mapping ({"name": "ivf", "nlist", "nprobe"});
    # None = exact scan only (see ops/ann.py for the IVF design)
    method: Optional[dict] = None
    # unit-norm copy for cosine (precomputed at build)
    _normed: Optional[np.ndarray] = None
    _ivf: Any = None

    def normed(self) -> np.ndarray:
        if self._normed is None:
            n = np.linalg.norm(self.values, axis=1, keepdims=True)
            self._normed = (self.values / np.maximum(n, 1e-12)).astype(np.float32)
        return self._normed

    def ivf(self):
        """Lazily built balanced-IVF index (deterministic: same data ->
        same index, so persistence only records the method, not arrays)."""
        if self._ivf is None and self.method and self.method.get("name") == "ivf":
            from ..ops.ann import build_ivf
            src = self.normed() if self.similarity == "cosine" else self.values
            self._ivf = build_ivf(src, self.present,
                                  nlist=self.method.get("nlist"),
                                  nprobe=self.method.get("nprobe"))
        return self._ivf


@dataclass
class TextFieldStats:
    doc_count: int = 0        # docs containing this field
    sum_dl: int = 0           # total tokens across docs


@dataclass
class NestedBlock:
    """Block-join children for one nested path: a full child-space Segment
    (its docs are the nested objects, fields keyed by dotted path) plus the
    child->parent doc map. The reference stores children as adjacent Lucene
    docs in the parent's block (NestedObjectMapper/ToParentBlockJoinQuery);
    here the child space is its own CSR segment and the join is a device
    scatter-reduce over `parent_of`."""

    child: "Segment"
    parent_of: np.ndarray  # i32[child.ndocs], nondecreasing (doc order)

    def children_of(self, parent_doc: int) -> Tuple[int, int]:
        a = int(np.searchsorted(self.parent_of, parent_doc, side="left"))
        b = int(np.searchsorted(self.parent_of, parent_doc, side="right"))
        return a, b


class Segment:
    """One immutable searchable unit (analog of a Lucene segment + its
    SegmentReader, reference `index/engine/Engine.java#acquireSearcher`)."""

    _seq = 0

    def __init__(self, name: str, ndocs: int,
                 postings: Dict[str, PostingsBlock],
                 numeric_cols: Dict[str, NumericColumn],
                 keyword_cols: Dict[str, KeywordColumn],
                 geo_cols: Dict[str, GeoColumn],
                 doc_lens: Dict[str, np.ndarray],
                 text_stats: Dict[str, TextFieldStats],
                 ids: List[str], sources: List[dict],
                 seq_nos: Optional[np.ndarray] = None,
                 vector_cols: Optional[Dict[str, VectorColumn]] = None,
                 nested: Optional[Dict[str, NestedBlock]] = None,
                 shape_cols: Optional[Dict[str, ShapeColumn]] = None,
                 stored_vals: Optional[list] = None,
                 codec_version: int = CODEC_V1):
        Segment._seq += 1
        self.uid = Segment._seq  # stable identity (id() can be reused post-GC)
        self.name = name
        self.ndocs = ndocs
        self.postings = postings
        self.numeric_cols = numeric_cols
        self.keyword_cols = keyword_cols
        self.geo_cols = geo_cols
        self.vector_cols = vector_cols or {}
        self.shape_cols = shape_cols or {}
        # per-doc {field: [raw values]} for store=true fields (reference
        # stored fields, independent of _source)
        self.stored_vals = stored_vals
        # term_vector offsets per field -> per-doc [(term, pos, start, end)]
        self.term_vectors: Optional[Dict[str, list]] = None
        self.doc_lens = doc_lens
        self.text_stats = text_stats
        self.nested: Dict[str, NestedBlock] = nested or {}
        self.ids = ids
        self.sources = sources
        self.seq_nos = seq_nos if seq_nos is not None else np.zeros(ndocs, dtype=np.int64)
        self.live = np.ones(ndocs, dtype=bool)
        self.live_gen = 0
        self.id2doc: Dict[str, int] = {d: i for i, d in enumerate(ids)}
        # per-device host->HBM residency: key None = process default device;
        # replicas re-host the SAME immutable arrays on their own device
        # (segment replication, reference indices/replication/)
        self._device_cache: Dict[Any, dict] = {}
        self._device_live_dirty: Dict[Any, bool] = {}
        # segment codec (CODEC_V1 | CODEC_V2): consumers branching on the
        # posting layout consult this attribute (oslint OSL507)
        self.codec_version = int(codec_version)
        # v2 fields whose f32 tf plane has been promoted back onto the
        # device (exact-scoring programs on codec-v2 segments request it
        # lazily via ensure_device_tfs; the hot impact path never does)
        self._tf_promoted: set = set()

    # ---------------- arrival-order tie ranks ----------------

    def tie_ranks(self) -> Optional[np.ndarray]:
        """Arrival-rank tie-break plane, or None when internal doc order
        IS arrival order (every segment the BP reorder pass has not
        touched — ids are assigned in write order and merges
        concatenate, so seq_nos ascend with doc id). After the merge-time
        doc-id reorder (index/reorder.py) score ties must still break in
        a layout-invariant order — the reorder parity contract: the same
        corpus indexed with and without the permutation serves
        byte-identical pages — so serving-path selections/sorts key ties
        on rank-of-seq_no instead of the (permuted) internal id. Lazy,
        cached; i64[ndocs] when present."""
        if "_tie_rank" not in self.__dict__:
            # gate on the explicit reorder marker, NOT a seq_no shape
            # heuristic: ordinary tiered merges concatenate segments in
            # live_count order, so never-reordered segments routinely
            # carry non-monotonic seq_nos — inferring "reordered" from
            # that would change their historical tie semantics (and tax
            # every query with the tie machinery). apply_permutation
            # pins the exact plane; this branch only reconstructs it
            # for marked segments reloaded without a persisted plane.
            s = np.asarray(self.seq_nos, np.int64)
            if not self.__dict__.get("_reordered") or len(s) < 2 \
                    or bool(np.all(np.diff(s) >= 0)):
                self.__dict__["_tie_rank"] = None
            else:
                tr = np.empty(len(s), np.int64)
                tr[np.argsort(s, kind="stable")] = np.arange(
                    len(s), dtype=np.int64)
                self.__dict__["_tie_rank"] = tr
        return self.__dict__["_tie_rank"]

    # ---------------- codec v2: impact planes ----------------

    def build_impacts(self, bits: Optional[int] = None,
                      feature_fields: Sequence[str] = ()) -> None:
        """Build quantized impact planes for every text-scored field
        (fields with a doc-length column) and stamp the segment codec v2.
        `feature_fields` names rank_features/sparse_vector fields whose
        mapping opted into `index_impacts`: those get a FEATURE plane
        (model-assigned weights quantized directly, kind="feature") so
        `neural_sparse` serves through the impact ladder.
        Idempotent; used by build_segment/merge and by direct CSR corpus
        wrappers (bench.py, scripts/hbm_report.py)."""
        feature_fields = set(feature_fields)
        for f, pb in self.postings.items():
            if pb.impact is not None:
                continue
            if f in feature_fields and f not in self.doc_lens:
                pb.impact = build_feature_impact_plane(pb, bits=bits)
                continue
            if f not in self.doc_lens:
                continue
            st = self.text_stats.get(f)
            avgdl = (st.sum_dl / st.doc_count
                     if st is not None and st.doc_count > 0 else None)
            pb.impact = build_impact_plane(pb, self.doc_lens.get(f),
                                           avgdl=avgdl, bits=bits)
        for blk in self.nested.values():
            blk.child.build_impacts(bits=bits)
        self.codec_version = CODEC_V2

    def drop_impacts(self) -> None:
        """Demote to codec v1 (compat/ablation path): planes dropped,
        device residency rebuilt with the tf plane on next use."""
        for pb in self.postings.values():
            pb.impact = None
        for blk in self.nested.values():
            blk.child.drop_impacts()
        self.codec_version = CODEC_V1
        self._tf_promoted = set()
        self.drop_device()

    # ---------------- live docs / deletes ----------------

    def delete_doc(self, local_doc: int) -> None:
        self.live[local_doc] = False
        for k in self._device_live_dirty:
            self._device_live_dirty[k] = True
        self.live_gen += 1  # invalidates live-dependent host caches

    @property
    def live_count(self) -> int:
        return int(self.live.sum())

    # ---------------- device residency ----------------

    @property
    def ndocs_pad(self) -> int:
        return next_pow2(self.ndocs)

    def device_arrays(self, device=None) -> dict:
        """The pytree of device-resident arrays consumed by `ops` kernels.
        Shapes are padded to pow2 buckets; structure is stable across segments
        of the same index so jitted plans re-hit the XLA compile cache.
        `device`: re-host on a specific device (replica placement); None =
        the process default."""
        import jax
        import jax.numpy as jnp

        key = device
        from ..obs.hbm_ledger import LEDGER
        # recency signal for LRU pressure eviction (lock-free hot path)
        LEDGER.touch(self, key)
        # SNAPSHOT the cache dict: pressure eviction (evict_device ->
        # drop_device) swaps `_device_cache` for a fresh dict rather than
        # mutating it, so a reader holding this reference keeps a valid
        # entry even when the evictor fires between its membership check
        # and its deref — the arrays stay alive until the last consumer
        # drops them
        cache = self._device_cache
        if key not in cache:
            # per-SEGMENT build lock: two request threads racing the same
            # (segment, device) miss would otherwise both build and both
            # charge the breaker (only one dict entry wins but both
            # finalizers release — a persistent double-charge), while
            # builds of DIFFERENT segments still overlap. dict.setdefault
            # is atomic under the GIL, so every racer gets the same lock;
            # reentrant because a parent's build recurses into nested
            # children (child locks are acquired parent->child, acyclic).
            lock = self.__dict__.setdefault(
                "_device_build_lock", _BuildLock())
            with lock:
                # the evictor takes this same lock, so the re-read below
                # cannot race a drop of THIS segment's residency
                cache = self._device_cache
                if key not in cache:
                    self._build_device_arrays(key, device)
                    cache = self._device_cache
        entry = cache[key]
        # `"live" not in entry` backstops a torn (old-cache, new-dirty)
        # pair: a stale reader's dirty=False write must never leave a
        # freshly rebuilt entry serving without its live plane
        if self._device_live_dirty.get(key, True) or "live" not in entry:
            live = _pad_to(self.live.astype(np.float32), self.ndocs_pad,
                           np.float32(0))
            entry["live"] = (
                # constant-size live plane, charged by the
                # _build_device_arrays ledger registration
                jnp.asarray(live) if device is None
                else jax.device_put(live, device))  # oslint: disable=OSL506
            self._device_live_dirty[key] = False
        return entry

    def _build_device_arrays(self, key, device) -> None:
        """Build + breaker-charge one (segment, device) cache entry.
        Caller holds _DEVICE_BUILD_LOCK and has re-checked the cache, so
        exactly one thread ever charges a given entry."""
        _t_dev = time.perf_counter()
        import jax.numpy as jnp

        if device is not None:
            jnp = _DevicePut(device)  # route jnp.asarray onto the device
        dpad = self.ndocs_pad
        post = {f: _post_field_arrays(
                    pb, jnp,
                    with_tfs=(pb.impact is None or f in self._tf_promoted))
                for f, pb in self.postings.items()}
        ncols = {f: _num_field_arrays(col, dpad, jnp)
                 for f, col in self.numeric_cols.items()}
        kcols = {f: _kw_field_arrays(col, dpad, jnp)
                 for f, col in self.keyword_cols.items()}
        vcols = {}
        for f, col in self.vector_cols.items():
            dims = col.values.shape[1]
            dpad128 = ((dims + 127) // 128) * 128  # MXU lane alignment
            mat = np.zeros((dpad, dpad128), np.float32)
            src = col.normed() if col.similarity == "cosine" else col.values
            mat[: self.ndocs, :dims] = src
            vcols[f] = {
                "mat": jnp.asarray(mat),
                "present": jnp.asarray(_pad_to(col.present, dpad, False)),
            }
            ivf = col.ivf()
            if ivf is not None:
                # nlist padded pow2; padding rows are invalid (cvalid
                # False -> -inf centroid score, lists slots -1)
                lpad = next_pow2(ivf.nlist)
                cent = np.zeros((lpad, dpad128), np.float32)
                cent[: ivf.nlist, :dims] = ivf.centroids
                lists = np.full((lpad, ivf.cap), -1, np.int32)
                lists[: ivf.nlist] = ivf.lists
                cvalid = np.zeros(lpad, bool)
                cvalid[: ivf.nlist] = True
                vcols[f]["ivf_centroids"] = jnp.asarray(cent)
                vcols[f]["ivf_lists"] = jnp.asarray(lists)
                vcols[f]["ivf_cvalid"] = jnp.asarray(cvalid)
        gcols = {f: _geo_field_arrays(col, dpad, jnp)
                 for f, col in self.geo_cols.items()}
        dls = {f: jnp.asarray(_pad_to(dl.astype(np.float32), dpad, np.float32(0)))
               for f, dl in self.doc_lens.items()}
        # NOTE: values must all be arrays — plain ints would become traced
        # jit arguments and poison static shape derivation downstream
        nst = {}
        for path, blk in self.nested.items():
            carr = dict(blk.child.device_arrays(device))
            cpad = blk.child.ndocs_pad
            # padded children map to parent 0 but carry live=0, so every
            # scatter-reduce contribution from padding is identically zero
            carr["parent"] = jnp.asarray(
                _pad_to(blk.parent_of.astype(np.int32), cpad, np.int32(0)))
            nst[path] = carr
        self._device_cache[key] = {
            "postings": post, "numeric": ncols, "keyword": kcols, "geo": gcols,
            "vector": vcols, "doc_lens": dls, "nested": nst,
        }
        # attributed only while a refresh/merge build is collecting —
        # lazy query-time promotion hits the no-op path
        note_stage("device_promote", time.perf_counter() - _t_dev)
        from ..obs.hbm_ledger import LEDGER
        # register THIS segment's new device residency with the HBM
        # ledger (which derives the breaker charge): every group built
        # above, the per-path "parent" maps, and the live plane
        # (constant size across dirty rebuilds). The nested children's
        # own arrays are registered by their recursive device_arrays()
        # calls — counting them here would double-bill. Codec v2 splits
        # the quantized impact planes out into their own `impact_postings`
        # tenant (and the host block-max sidecar into an advisory
        # `block_max` tenant) so the format rev's footprint delta is a
        # first-class ledger observable.
        imp_bytes = sum(int(fa["impacts"].nbytes)
                        for fa in post.values() if "impacts" in fa)
        # dense-vector residency is its own tenant pair (ISSUE 15: kNN
        # as a first-class serving citizen needs its HBM bytes visible):
        # the doc matrices under `vector_columns`, the balanced-IVF
        # probe structures (centroids + dense lists + validity) under
        # `ann_ivf` — both still charged, just attributed
        ivf_bytes = sum(int(v[k2].nbytes) for v in vcols.values()
                        for k2 in ("ivf_centroids", "ivf_lists",
                                   "ivf_cvalid") if k2 in v)
        vec_bytes = _tree_nbytes(vcols) - ivf_bytes
        nbytes = sum(_tree_nbytes(self._device_cache[key][g])
                     for g in ("postings", "numeric", "keyword",
                               "geo", "doc_lens"))
        nbytes -= imp_bytes
        nbytes += sum(int(c["parent"].nbytes)
                      for c in nst.values())
        nbytes += self.ndocs_pad * 4          # live plane (f32)
        allocs = []
        try:
            # evictor: under breaker pressure the ledger may call
            # evict_device (weakly held) to reclaim this whole plane
            # group — the entry rebuilds transparently on next use
            allocs.append(LEDGER.register(
                "segment_columns", nbytes, owner=self, segment=self,
                device=key, label=f"segment-device[{self.name}]",
                evictor=self.evict_device))
            if vec_bytes:
                allocs.append(LEDGER.register(
                    "vector_columns", vec_bytes, owner=self,
                    segment=self, device=key,
                    label=f"segment-vectors[{self.name}]",
                    evictor=self.evict_device))
            if ivf_bytes:
                allocs.append(LEDGER.register(
                    "ann_ivf", ivf_bytes, owner=self, segment=self,
                    device=key, label=f"segment-ivf[{self.name}]",
                    evictor=self.evict_device))
            if imp_bytes:
                allocs.append(LEDGER.register(
                    "impact_postings", imp_bytes, owner=self, segment=self,
                    device=key, label=f"segment-impacts[{self.name}]",
                    evictor=self.evict_device))
                sidecar = sum(pb.impact.block_max.nbytes
                              + pb.impact.block_off.nbytes
                              + pb.impact.block_starts.nbytes
                              for pb in self.postings.values()
                              if pb.impact is not None)
                # the sidecar is HOST-resident plan metadata (the XLA
                # prune selects blocks before launch); advisory so the
                # byte is visible per tenant without billing the breaker
                allocs.append(LEDGER.register(
                    "block_max", sidecar, owner=self, segment=self,
                    device=key, charge=False,
                    label=f"segment-blockmax[{self.name}]"))
        except Exception:
            # tripped mid-way: roll back what was charged and drop the
            # entry so a later retry re-attempts instead of serving free
            for a in allocs:
                LEDGER.release(a)
            del self._device_cache[key]
            raise
        self.__dict__.setdefault("_hbm_allocs", {}).setdefault(
            key, []).extend(allocs)
        # full-residency promotion: the partial per-field arrays this
        # device key accumulated via pruned_arrays() are now redundant —
        # the full pytree supersedes them (pruned_arrays serves from it
        # on every later call). Drop them and release their ledger
        # charges, or the overlapping term arrays stay double-counted
        # for the segment's lifetime.
        fcache = self.__dict__.get("_field_device_cache")
        if fcache:
            for ck in [c for c in fcache if c[0] == key]:
                del fcache[ck]
        fallocs = self.__dict__.get("_field_device_allocs")
        if fallocs:
            for ck in [c for c in fallocs if c[0] == key]:
                LEDGER.release(fallocs.pop(ck))
        self._device_live_dirty[key] = True

    def ensure_device_tfs(self, field: str, device=None) -> None:
        """Promote the f32 tf plane of one codec-v2 field back onto the
        device. The v2 layout ships (doc_ids, quantized impacts) only —
        the BM25 hot path never touches tf — but exact-scoring program
        variants (non-BM25 similarities, combined_fields BM25F, the
        impact ladder's dense escalation) still need it. Called at
        prepare time (host side, before any launch); one upload per
        (segment, field), every current and future device key included."""
        pb = self.postings.get(field)
        if pb is None or pb.impact is None or field in self._tf_promoted:
            return
        import jax
        import jax.numpy as _jnp
        from ..obs.hbm_ledger import LEDGER
        lock = self.__dict__.setdefault(
            "_device_build_lock", _BuildLock())
        with lock:
            if field in self._tf_promoted:
                return
            ppad = next_pow2(pb.size)
            tf_host = _pad_to(pb.tfs.astype(np.float32), ppad,
                              np.float32(0))
            for key, cache in self._device_cache.items():
                fa = cache["postings"].get(field)
                if fa is None or "tfs" in fa:
                    continue
                arr = (_jnp.asarray(tf_host) if key is None
                       else jax.device_put(tf_host, key))
                alloc = LEDGER.register(
                    "postings_tfs", int(arr.nbytes), owner=self,
                    segment=self, device=key,
                    label=f"segment-tfs[{self.name}][{field}]",
                    evictor=self.evict_device)
                fa["tfs"] = arr
                self.__dict__.setdefault("_hbm_allocs", {}).setdefault(
                    key, []).append(alloc)
            # future device builds include the plane from the start
            self._tf_promoted.add(field)

    def pruned_arrays(self, device, needs: Dict[str, set]) -> dict:
        """Device arrays for ONLY the named fields — the filter-mask path
        uses this so building a status-term mask never ships the body
        postings to HBM (device_arrays is all-or-nothing; jit argument
        pruning happens after the transfer already paid). Per-field device
        arrays are cached and ledger-registered as `partial_columns`; a
        later full device_arrays() build PROMOTES this partial residency —
        the per-field arrays are dropped and their charges released, so
        overlapping term arrays are never double-counted.
        `needs` keys: postings / numeric / keyword / geo -> field sets."""
        key = device
        from ..obs.hbm_ledger import LEDGER
        LEDGER.touch(self, key)
        if key in self._device_cache:
            # the full pytree already exists: serve from it (no extra HBM)
            return self.device_arrays(device)
        # the SAME per-segment build lock device_arrays takes: two racing
        # partial builds of one field must not both register (the loser's
        # charge would leak until segment GC), and the full build's
        # promotion sweep iterates these dicts under this lock
        lock = self.__dict__.setdefault(
            "_device_build_lock", _BuildLock())
        with lock:
            return self._pruned_arrays_locked(key, device, needs)

    def _pruned_arrays_locked(self, key, device, needs: Dict[str, set]
                              ) -> dict:
        import jax
        import jax.numpy as _jnp

        from ..obs.hbm_ledger import LEDGER

        if key in self._device_cache:
            # a racing full build won: serve the promoted pytree
            return self.device_arrays(device)
        jnp = _DevicePut(device) if device is not None else _jnp
        cache = self.__dict__.setdefault("_field_device_cache", {})
        allocs = self.__dict__.setdefault("_field_device_allocs", {})
        dpad = self.ndocs_pad

        def field(group: str, f: str, builder):
            k = (key, group, f)
            if k not in cache:
                arrs = builder()
                allocs[k] = LEDGER.register(
                    "partial_columns", _tree_nbytes(arrs), owner=self,
                    segment=self, device=key,
                    label=f"segment-partial[{self.name}][{group}.{f}]",
                    evictor=self.evict_device)
                cache[k] = arrs
            return cache[k]

        out: Dict[str, Any] = {"postings": {}, "numeric": {}, "keyword": {},
                               "geo": {}, "vector": {}, "doc_lens": {},
                               "nested": {}}
        for f in needs.get("postings", ()):
            pb = self.postings.get(f)
            if pb is not None:
                # filter-mask views never score: no tf plane (v1 fields
                # keep it — their layout has nothing else) and no impacts
                out["postings"][f] = field(
                    "postings", f, lambda pb=pb: _post_field_arrays(
                        pb, jnp, with_tfs=False, with_impacts=False))
        for f in needs.get("numeric", ()):
            col = self.numeric_cols.get(f)
            if col is not None:
                out["numeric"][f] = field(
                    "numeric", f,
                    lambda col=col: _num_field_arrays(col, dpad, jnp))
        for f in needs.get("keyword", ()):
            col = self.keyword_cols.get(f)
            if col is not None:
                out["keyword"][f] = field(
                    "keyword", f,
                    lambda col=col: _kw_field_arrays(col, dpad, jnp))
        for f in needs.get("geo", ()):
            col = self.geo_cols.get(f)
            if col is not None:
                out["geo"][f] = field(
                    "geo", f,
                    lambda col=col: _geo_field_arrays(col, dpad, jnp))
        for f in needs.get("doc_lens", ()):
            dl = self.doc_lens.get(f)
            if dl is not None:
                out["doc_lens"][f] = field(
                    "doc_lens", f, lambda dl=dl: jnp.asarray(
                        _pad_to(dl.astype(np.float32), dpad, np.float32(0))))
        lk = (key, "#live", self.live_gen)
        if lk not in cache:
            for stale in [c for c in cache if c[1] == "#live"]:
                del cache[stale]
                LEDGER.release(allocs.pop(stale, None))
            live = _pad_to(self.live.astype(np.float32), self.ndocs_pad,
                           np.float32(0))
            arr = (jax.device_put(live, device) if device is not None
                   else _jnp.asarray(live))
            allocs[lk] = LEDGER.register(
                "partial_columns", int(arr.nbytes), owner=self,
                segment=self, device=key,
                label=f"segment-partial[{self.name}][live]")
            cache[lk] = arr
        out["live"] = cache[lk]
        return out

    def evict_device(self) -> bool:
        """Pressure-eviction hook (obs/hbm_ledger.py `_evict_lru`): drop
        this segment's device residency UNLESS a build is in flight —
        the ledger calls this with its own lock held, so blocking on the
        build lock here would invert the (build lock -> ledger lock)
        order every `_build_device_arrays` takes. A depth check backs up
        the non-blocking acquire: the evictor often runs on the builder's
        OWN thread (a build's ledger registration triggers eviction of a
        sibling this thread is also mid-building, e.g. a nested parent),
        where the reentrant acquire would succeed. Returns True when the
        residency was actually released."""
        held = []
        try:
            # drop_device recurses into nested children, and the
            # compiler builds child planes (ensure_device_tfs) under the
            # CHILD's lock only — so the whole family must be idle, not
            # just the parent, or a pressure evict rips a plane out from
            # under a mid-flight child build
            stack = [self]
            while stack:
                s = stack.pop()
                lock = s.__dict__.setdefault(
                    "_device_build_lock", _BuildLock())
                if not lock.acquire(blocking=False):
                    return False
                held.append(lock)
                if lock.depth > 1:  # this thread is building this segment
                    return False
                stack.extend(blk.child for blk in s.nested.values())
            self.drop_device()
            return True
        finally:
            for lock in reversed(held):
                lock.release()

    def drop_device(self) -> None:
        from ..obs.hbm_ledger import LEDGER
        self._device_cache = {}
        self._device_live_dirty = {}
        self.__dict__.pop("_field_device_cache", None)
        # eager release: the arrays are gone NOW, so the ledger (and the
        # derived breaker charge) must not wait for the segment's GC
        for allocs in self.__dict__.pop("_hbm_allocs", {}).values():
            for alloc in allocs:
                LEDGER.release(alloc)
        for alloc in self.__dict__.pop("_field_device_allocs", {}).values():
            LEDGER.release(alloc)
        for blk in self.nested.values():
            blk.child.drop_device()

    # ---------------- persistence (flush/commit) ----------------

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {"live": self.live, "seq_nos": self.seq_nos}
        tr = self.__dict__.get("_tie_rank")
        if tr is not None:
            # persist the pinned arrival plane verbatim: the seq_no
            # reconstruction on load is only an approximation when the
            # pre-permutation concatenation wasn't seq-ascending (tiered
            # merges order inputs by live_count) or seq_nos are
            # degenerate (direct-CSR corpora default to zeros) — the
            # plane must be byte-identical across a restart or tie pages
            # drift from their replicas
            arrays["tie_rank"] = tr
        meta: Dict[str, Any] = {"name": self.name, "ndocs": self.ndocs,
                                "codec": self.codec_version,
                                # BP reorder pass already ran (index/
                                # reorder.py) — without this, the first
                                # force_merge after a restart re-merges
                                # and re-reorders an already-clustered
                                # segment (~minutes at 1M docs)
                                "reordered": bool(
                                    self.__dict__.get("_reordered", False)),
                                "postings": {}, "numeric": {}, "keyword": {}, "geo": {},
                                "impacts": {},
                                "text_stats": {f: [s.doc_count, s.sum_dl]
                                               for f, s in self.text_stats.items()}}
        derived = self.__dict__.get("_derived_names", set())
        for f, pb in self.postings.items():
            if f in derived:
                continue   # derived fields are query-time only, never persisted
            key = f"post__{f}"
            arrays[f"{key}__starts"] = pb.starts
            arrays[f"{key}__doc_ids"] = pb.doc_ids
            arrays[f"{key}__tfs"] = pb.tfs
            if pb.pos_starts is not None:
                arrays[f"{key}__pos_starts"] = pb.pos_starts
                arrays[f"{key}__positions"] = pb.positions
            if pb.impact is not None:
                ip = pb.impact
                arrays[f"imp__{f}__q"] = ip.q
                arrays[f"imp__{f}__bstarts"] = ip.block_starts
                arrays[f"imp__{f}__boff"] = ip.block_off
                arrays[f"imp__{f}__bmax"] = ip.block_max
                meta["impacts"][f] = {"scale": ip.scale, "bits": ip.bits,
                                      "k1": ip.k1, "b": ip.b,
                                      "avgdl": ip.avgdl,
                                      "dl_max": ip.dl_max,
                                      "kind": ip.kind}
            meta["postings"][f] = {"vocab_file": True, "positional": pb.pos_starts is not None}
            with open(os.path.join(path, f"vocab__{f.replace('/', '_')}.txt"), "w") as fh:
                fh.write("\n".join(pb.vocab))
        for f, col in self.numeric_cols.items():
            if f in derived:
                continue
            arrays[f"num__{f}__values"] = col.values
            arrays[f"num__{f}__present"] = col.present
            meta["numeric"][f] = {"kind": col.kind}
        for f, col in self.keyword_cols.items():
            if f in derived:
                continue
            arrays[f"kw__{f}__starts"] = col.starts
            arrays[f"kw__{f}__ords"] = col.ords
            arrays[f"kw__{f}__docs"] = col.doc_of_value
            arrays[f"kw__{f}__min_ord"] = col.min_ord
            meta["keyword"][f] = {"vocab_file": True}
            with open(os.path.join(path, f"kwvocab__{f.replace('/', '_')}.txt"), "w") as fh:
                fh.write("\n".join(col.vocab))
        for f, col in self.geo_cols.items():
            arrays[f"geo__{f}__lat"] = col.lat
            arrays[f"geo__{f}__lon"] = col.lon
            arrays[f"geo__{f}__present"] = col.present
        for f, col in self.vector_cols.items():
            arrays[f"vec__{f}__values"] = col.values
            arrays[f"vec__{f}__present"] = col.present
            meta["vector"] = meta.get("vector", {})
            meta["vector"][f] = {"similarity": col.similarity,
                                 "method": col.method}
        for f, dl in self.doc_lens.items():
            arrays[f"dl__{f}"] = dl
        meta["shape"] = sorted(self.shape_cols)
        for f, col in self.shape_cols.items():
            arrays[f"shape__{f}__bbox"] = np.stack(
                [col.minx, col.miny, col.maxx, col.maxy])
            arrays[f"shape__{f}__present"] = col.present
            with open(os.path.join(path,
                                   f"shapes__{f.replace('/', '_')}.json"),
                      "w") as fh:
                json.dump(col.specs, fh)
        meta["nested"] = sorted(self.nested)
        for npath, blk in self.nested.items():
            sub = os.path.join(path, f"nested__{npath.replace('/', '_')}")
            blk.child.save(sub)
            arrays[f"nested__{npath}__parent"] = blk.parent_of
        np.savez_compressed(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "meta.json"), "w") as fh:
            json.dump(meta, fh)
        with open(os.path.join(path, "stored.jsonl"), "w") as fh:
            for i, src in enumerate(self.sources):
                rec = {"_id": self.ids[i], "_source": src}
                if self.stored_vals and self.stored_vals[i]:
                    rec["_stored"] = self.stored_vals[i]
                fh.write(json.dumps(rec) + "\n")
        if self.term_vectors:
            with open(os.path.join(path, "term_vectors.json"), "w") as fh:
                json.dump({f: col for f, col in self.term_vectors.items()},
                          fh)

    @classmethod
    def load(cls, path: str) -> "Segment":
        with open(os.path.join(path, "meta.json")) as fh:
            meta = json.load(fh)
        arrays = np.load(os.path.join(path, "arrays.npz"), allow_pickle=False)
        ids, sources, stored_vals = [], [], []
        any_stored = False
        with open(os.path.join(path, "stored.jsonl")) as fh:
            for line in fh:
                rec = json.loads(line)
                ids.append(rec["_id"])
                sources.append(rec["_source"])
                sv = rec.get("_stored")
                any_stored = any_stored or bool(sv)
                stored_vals.append(sv)
        postings = {}
        for f, pmeta in meta["postings"].items():
            with open(os.path.join(path, f"vocab__{f.replace('/', '_')}.txt")) as fh:
                content = fh.read()
                vocab = content.split("\n") if content else []
            key = f"post__{f}"
            postings[f] = PostingsBlock(
                field=f, vocab=vocab, terms={t: i for i, t in enumerate(vocab)},
                starts=arrays[f"{key}__starts"], doc_ids=arrays[f"{key}__doc_ids"],
                tfs=arrays[f"{key}__tfs"],
                pos_starts=arrays.get(f"{key}__pos_starts"),
                positions=arrays.get(f"{key}__positions"))
            im = meta.get("impacts", {}).get(f)
            if im is not None:
                postings[f].impact = ImpactPlane(
                    q=arrays[f"imp__{f}__q"], scale=float(im["scale"]),
                    bits=int(im["bits"]), k1=float(im["k1"]),
                    b=float(im["b"]), avgdl=float(im["avgdl"]),
                    dl_max=int(im["dl_max"]),
                    block_starts=arrays[f"imp__{f}__bstarts"],
                    block_off=arrays[f"imp__{f}__boff"],
                    block_max=arrays[f"imp__{f}__bmax"],
                    kind=str(im.get("kind", "bm25")))
        numeric = {f: NumericColumn(f, m["kind"], arrays[f"num__{f}__values"],
                                    arrays[f"num__{f}__present"])
                   for f, m in meta["numeric"].items()}
        keyword = {}
        for f in meta["keyword"]:
            with open(os.path.join(path, f"kwvocab__{f.replace('/', '_')}.txt")) as fh:
                content = fh.read()
                kvocab = content.split("\n") if content else []
            keyword[f] = KeywordColumn(f, kvocab, arrays[f"kw__{f}__starts"],
                                       arrays[f"kw__{f}__ords"], arrays[f"kw__{f}__docs"],
                                       arrays[f"kw__{f}__min_ord"])
        geo = {f: GeoColumn(f, arrays[f"geo__{f}__lat"], arrays[f"geo__{f}__lon"],
                            arrays[f"geo__{f}__present"])
               for f in meta["geo"]}
        vectors = {f: VectorColumn(f, arrays[f"vec__{f}__values"],
                                   arrays[f"vec__{f}__present"],
                                   m.get("similarity", "cosine"),
                                   method=m.get("method"))
                   for f, m in meta.get("vector", {}).items()}
        doc_lens = {k[len("dl__"):]: arrays[k] for k in arrays.files if k.startswith("dl__")}
        shapes = {}
        for f in meta.get("shape", []):
            with open(os.path.join(path,
                                   f"shapes__{f.replace('/', '_')}.json")) as fh:
                specs = json.load(fh)
            bbox = arrays[f"shape__{f}__bbox"]
            shapes[f] = ShapeColumn(f, specs, bbox[0], bbox[1], bbox[2],
                                    bbox[3], arrays[f"shape__{f}__present"])
        nested = {}
        for npath in meta.get("nested", []):
            sub = os.path.join(path, f"nested__{npath.replace('/', '_')}")
            nested[npath] = NestedBlock(cls.load(sub),
                                        arrays[f"nested__{npath}__parent"])
        seg = cls(meta["name"], meta["ndocs"], postings, numeric, keyword, geo, doc_lens,
                  {f: TextFieldStats(dc, sd) for f, (dc, sd) in meta["text_stats"].items()},
                  ids, sources, seq_nos=arrays["seq_nos"], vector_cols=vectors,
                  nested=nested, shape_cols=shapes,
                  stored_vals=stored_vals if any_stored else None,
                  # pre-rev metas carry no codec entry: those are v1
                  # segments and keep serving unchanged
                  codec_version=int(meta.get("codec", CODEC_V1)))
        seg.live = arrays["live"].copy()
        if meta.get("reordered"):
            seg.__dict__["_reordered"] = True
            # pin exactly what was saved: a reordered segment persists
            # its plane verbatim (save()), and a no-op-marked segment
            # (pass ran, nothing clustered) has none — reconstructing
            # one from seq_nos here would invent a tie order the
            # pre-restart process never served
            seg.__dict__["_tie_rank"] = (arrays["tie_rank"]
                                         if "tie_rank" in arrays else None)
        seg.id2doc = {d: i for i, d in enumerate(ids) if seg.live[i]}
        tv_path = os.path.join(path, "term_vectors.json")
        if os.path.exists(tv_path):
            with open(tv_path) as fh:
                raw = json.load(fh)
            seg.term_vectors = {
                f: [[tuple(e) for e in col] if col else None
                    for col in cols]
                for f, cols in raw.items()}
        return seg


def _post_field_arrays(pb: "PostingsBlock", jnp, with_tfs: bool = True,
                       with_impacts: bool = True) -> dict:
    """Device arrays of one CSR postings field. Codec v2 fields ship the
    quantized impact plane instead of the f32 tf plane (callers decide
    via `with_tfs`; exact-scoring programs promote tf back lazily through
    Segment.ensure_device_tfs) — the resident postings bytes per slot drop
    from 8 (doc+tf) to 5/6 (doc+u8/u16 impact)."""
    ppad = next_pow2(pb.size)
    rpad = next_pow2(pb.nterms + 2)
    starts = _pad_to(pb.starts.astype(np.int32), rpad, np.int32(pb.size))
    out = {
        "starts": jnp.asarray(starts),
        "doc_ids": jnp.asarray(_pad_to(pb.doc_ids.astype(np.int32), ppad, INT32_SENTINEL)),
    }
    if with_tfs or pb.impact is None:
        out["tfs"] = jnp.asarray(
            _pad_to(pb.tfs.astype(np.float32), ppad, np.float32(0)))
    if with_impacts and pb.impact is not None:
        out["impacts"] = jnp.asarray(
            _pad_to(pb.impact.q, ppad, pb.impact.q.dtype.type(0)))
    return out


def _num_field_arrays(col: "NumericColumn", dpad: int, jnp) -> dict:
    if col.kind in ("int", "uint"):
        hi, lo = split_i64(col.values)
        # unsigned_long stores biased i64 (order-exact); the f32
        # agg/script view unbiases back to the real magnitude
        f32v = (col.values.astype(np.float64) + float(1 << 63)
                if col.kind == "uint" else col.values).astype(np.float32)
        return {
            "hi": jnp.asarray(_pad_to(hi, dpad, np.int32(0))),
            "lo": jnp.asarray(_pad_to(lo, dpad, np.int32(0))),
            "f32": jnp.asarray(_pad_to(f32v, dpad, np.float32(0))),
            "present": jnp.asarray(_pad_to(col.present, dpad, False)),
        }
    return {
        "f32": jnp.asarray(_pad_to(col.values.astype(np.float32), dpad, np.float32(0))),
        "present": jnp.asarray(_pad_to(col.present, dpad, False)),
    }


def _kw_field_arrays(col: "KeywordColumn", dpad: int, jnp) -> dict:
    vpad = next_pow2(len(col.ords))
    return {
        "ords": jnp.asarray(_pad_to(col.ords, vpad, np.int32(-1))),
        "doc_of_value": jnp.asarray(_pad_to(col.doc_of_value, vpad, INT32_SENTINEL)),
        "min_ord": jnp.asarray(_pad_to(col.min_ord, dpad, np.int32(-1))),
    }


def _geo_field_arrays(col: "GeoColumn", dpad: int, jnp) -> dict:
    return {
        "lat": jnp.asarray(_pad_to(col.lat, dpad, np.float32(0))),
        "lon": jnp.asarray(_pad_to(col.lon, dpad, np.float32(0))),
        "present": jnp.asarray(_pad_to(col.present, dpad, False)),
    }


def _pack_postings_python(parsed_docs: list, with_positions: bool) -> Dict[str, PostingsBlock]:
    """Pure-Python postings pack (dict accumulate -> sort -> CSR). Reference
    semantics: one posting per (term, doc) with tf; positions flattened in
    ascending order per posting."""
    field_term_docs: Dict[str, Dict[str, dict]] = {}
    field_term_pos: Dict[str, Dict[str, dict]] = {}
    for doc_i, pd in enumerate(parsed_docs):
        for fname, terms in pd.terms.items():
            td = field_term_docs.setdefault(fname, {})
            for t in terms:
                postings = td.setdefault(t, {})
                postings[doc_i] = postings.get(doc_i, 0) + 1
        if with_positions:
            for fname, tps in pd.positions.items():
                tp = field_term_pos.setdefault(fname, {})
                for t, p in tps:
                    tp.setdefault(t, {}).setdefault(doc_i, []).append(p)

    postings: Dict[str, PostingsBlock] = {}
    for fname, term_docs in field_term_docs.items():
        vocab = sorted(term_docs)
        terms = {t: i for i, t in enumerate(vocab)}
        lens = np.fromiter((len(term_docs[t]) for t in vocab), dtype=np.int64, count=len(vocab))
        starts = np.zeros(len(vocab) + 1, dtype=np.int64)
        np.cumsum(lens, out=starts[1:])
        total = int(starts[-1])
        doc_ids = np.empty(total, dtype=np.int32)
        tfs = np.empty(total, dtype=np.float32)
        pos_chunks: List[List[int]] = []
        pos_lens = np.zeros(total, dtype=np.int64) if with_positions else None
        k = 0
        tp = field_term_pos.get(fname, {})
        for t in vocab:
            d = term_docs[t]
            for doc_i in sorted(d):
                doc_ids[k] = doc_i
                tfs[k] = d[doc_i]
                if with_positions:
                    plist = tp.get(t, {}).get(doc_i, [])
                    pos_lens[k] = len(plist)
                    pos_chunks.append(plist)
                k += 1
        pos_starts = positions = None
        if with_positions:
            pos_starts = np.zeros(total + 1, dtype=np.int64)
            np.cumsum(pos_lens, out=pos_starts[1:])
            positions = np.fromiter((p for chunk in pos_chunks for p in chunk),
                                    dtype=np.int32, count=int(pos_starts[-1]))
        postings[fname] = PostingsBlock(fname, vocab, terms, starts, doc_ids, tfs,
                                        pos_starts, positions)
    return postings


def pack_postings(parsed_docs: list, with_positions: bool) -> Dict[str, PostingsBlock]:
    """Pack buffered per-doc term lists into CSR PostingsBlocks. Uses the
    native C++ packer (native/opensearch_native.cpp: intern -> sort ->
    CSR scan) when built; falls back to the Python path per-field otherwise
    (bit-identical output — tests/test_native.py asserts parity)."""
    from .. import native

    if not native.available():
        return _pack_postings_python(parsed_docs, with_positions)

    # flatten the token stream per field
    field_tokens: Dict[str, List[str]] = {}
    field_counts: Dict[str, List[Tuple[int, int]]] = {}
    field_pos: Dict[str, List[int]] = {}
    fallback_fields: set = set()
    for doc_i, pd in enumerate(parsed_docs):
        for fname, terms in pd.terms.items():
            bucket = field_tokens.setdefault(fname, [])  # empty lists still
            if not terms:                                # register the field
                continue
            bucket.extend(terms)
            field_counts.setdefault(fname, []).append((doc_i, len(terms)))
            if with_positions:
                pl = pd.positions.get(fname)
                if pl is not None:
                    if len(pl) != len(terms):
                        fallback_fields.add(fname)  # mis-aligned stream
                    field_pos.setdefault(fname, []).extend(p for _, p in pl)

    out: Dict[str, PostingsBlock] = {}
    python_fields: List[str] = []
    for fname, tokens in field_tokens.items():
        joined = "\x00".join(tokens)
        if fname in fallback_fields or (
                tokens and joined.count("\x00") != len(tokens) - 1):
            python_fields.append(fname)  # embedded NUL in a token
            continue
        pairs = field_counts.get(fname, [])
        docs = np.fromiter((d for d, _ in pairs), np.int32, count=len(pairs))
        cnts = np.fromiter((c for _, c in pairs), np.int64, count=len(pairs))
        doc_of = np.repeat(docs, cnts)
        has_pos = with_positions and fname in field_pos
        if has_pos and len(field_pos[fname]) != len(tokens):
            # positions for some docs but not others — mis-aligned stream,
            # take the Python fallback (same as the len(pl) != len(terms) guard)
            python_fields.append(fname)
            continue
        pos_arr = (np.fromiter(field_pos[fname], np.int32, count=len(tokens))
                   if has_pos else None)
        packer = native.Packer(with_positions=has_pos)
        packer.add(joined, len(tokens), doc_of, pos_arr)
        vocab, starts, doc_ids, tfs, pos_starts, positions = packer.finish()
        packer.close()
        if with_positions and not has_pos:
            # fields indexed without positions (keyword/ip) still carry an
            # all-empty positions CSR when the segment is positional — same
            # as the Python path
            pos_starts = np.zeros(len(doc_ids) + 1, dtype=np.int64)
            positions = np.empty(0, dtype=np.int32)
        out[fname] = PostingsBlock(fname, vocab, {t: i for i, t in enumerate(vocab)},
                                   starts, doc_ids, tfs, pos_starts, positions)
    if python_fields:
        sub = [type(pd)(doc_id=pd.doc_id, source=pd.source, routing=pd.routing,
                        terms={f: pd.terms[f] for f in python_fields if f in pd.terms},
                        positions={f: pd.positions[f] for f in python_fields
                                   if f in pd.positions})
               for pd in parsed_docs]
        out.update(_pack_postings_python(sub, with_positions))
    return out


def _numeric_kind(mappings: Mappings, fname: str) -> str:
    """Storage kind of one numeric doc-value column — shared by the
    in-memory and streaming builders so the two paths cannot diverge."""
    ft = mappings.resolve_field(fname)
    if fname.endswith(("#lo", "#hi")) and ft is None:
        # range-field bound columns: member type decides the kind
        from .mappings import RANGE_MEMBER
        rft = mappings.resolve_field(fname[:-3])
        member = RANGE_MEMBER.get(rft.type) if rft is not None else None
        return "float" if member in ("float", "double") else "int"
    if ft is not None and ft.type == "unsigned_long":
        return "uint"        # biased i64: exact order, unbiased f32 view
    return "float" if (ft is not None and ft.type in FLOAT_TYPES) else "int"


def feature_impact_fields(mappings: Mappings, fields) -> List[str]:
    """The subset of feature-postings fields whose mapping opted into
    `index_impacts` (rank_features/sparse_vector only) — the fields that
    get a codec-v2 FEATURE impact plane at build/merge time."""
    out = []
    for f in sorted(fields):
        ft = mappings.resolve_field(f)
        if ft is not None and getattr(ft, "index_impacts", False):
            out.append(f)
    return out


def build_segment(name: str, parsed_docs: list, mappings: Mappings,
                  seq_nos: Optional[List[int]] = None,
                  with_positions: bool = True) -> Segment:
    """Build an immutable segment from buffered parsed docs (the refresh path,
    analog of Lucene DWPT flush driven by reference
    `index/engine/InternalEngine.java#refresh`)."""
    ndocs = len(parsed_docs)
    ids = [d.doc_id for d in parsed_docs]
    sources = ([d.source for d in parsed_docs]
               if getattr(mappings, "source_enabled", True)
               else [{} for _ in parsed_docs])
    stored_vals = ([dict(d.stored) if d.stored else None
                    for d in parsed_docs]
                   if any(d.stored for d in parsed_docs) else None)
    term_vectors = None
    if any(d.offsets for d in parsed_docs):
        term_vectors = {}
        for doc_i, pd in enumerate(parsed_docs):
            for fname, offs in pd.offsets.items():
                col = term_vectors.setdefault(fname, [None] * ndocs)
                col[doc_i] = offs

    # ---- inverted fields ----
    doc_lens: Dict[str, np.ndarray] = {}
    text_stats: Dict[str, TextFieldStats] = {}
    for doc_i, pd in enumerate(parsed_docs):
        for fname, terms in pd.terms.items():
            ft = mappings.resolve_field(fname)
            if ft is not None and ft.type == "text":
                stats = text_stats.setdefault(fname, TextFieldStats())
                stats.doc_count += 1
                stats.sum_dl += len(terms)
                dl = doc_lens.setdefault(fname, np.zeros(ndocs, dtype=np.int64))
                dl[doc_i] = len(terms)

    _t_pack = time.perf_counter()
    postings = pack_postings(parsed_docs, with_positions)
    note_stage("pack", time.perf_counter() - _t_pack)

    # ---- feature postings (rank_features / sparse_vector): CSR rows are
    # features, "tf" carries the feature weight — the device scores them with
    # the same gather->scatter pass as terms (reference mapper-extras encodes
    # weights in the term frequency the same way) ----
    feat_fields = {f for pd in parsed_docs for f in pd.features}
    for fname in sorted(feat_fields):
        feat_docs: Dict[str, List[Tuple[int, float]]] = {}
        for doc_i, pd in enumerate(parsed_docs):
            for feat, w in pd.features.get(fname, {}).items():
                feat_docs.setdefault(feat, []).append((doc_i, w))
        vocab = sorted(feat_docs)
        terms = {t: i for i, t in enumerate(vocab)}
        starts = np.zeros(len(vocab) + 1, dtype=np.int64)
        flat: List[Tuple[int, float]] = []
        for i, t in enumerate(vocab):
            flat.extend(feat_docs[t])
            starts[i + 1] = len(flat)
        doc_ids = np.fromiter((d for d, _ in flat), np.int32, count=len(flat))
        tfs = np.fromiter((w for _, w in flat), np.float32, count=len(flat))
        postings[fname] = PostingsBlock(fname, vocab, terms, starts, doc_ids, tfs)

    # ---- doc values ----
    numeric_cols: Dict[str, NumericColumn] = {}
    keyword_cols: Dict[str, KeywordColumn] = {}
    geo_cols: Dict[str, GeoColumn] = {}
    num_fields = {f for pd in parsed_docs for f in pd.numerics}
    kw_fields = {f for pd in parsed_docs for f in pd.keywords}
    geo_fields = {f for pd in parsed_docs for f in pd.geos}
    vec_fields = {f for pd in parsed_docs for f in pd.vectors}

    for fname in num_fields:
        kind = _numeric_kind(mappings, fname)
        dtype = np.float64 if kind == "float" else np.int64
        values = np.zeros(ndocs, dtype=dtype)
        present = np.zeros(ndocs, dtype=bool)
        for doc_i, pd in enumerate(parsed_docs):
            vals = pd.numerics.get(fname)
            if vals:
                values[doc_i] = vals[0]
                present[doc_i] = True
        numeric_cols[fname] = NumericColumn(fname, kind, values, present)

    for fname in kw_fields:
        value_set = set()
        for pd in parsed_docs:
            value_set.update(pd.keywords.get(fname, ()))
        vocab = sorted(value_set)
        ord_of = {v: i for i, v in enumerate(vocab)}
        starts = np.zeros(ndocs + 1, dtype=np.int64)
        flat_ords: List[int] = []
        flat_docs: List[int] = []
        min_ord = np.full(ndocs, -1, dtype=np.int32)
        for doc_i, pd in enumerate(parsed_docs):
            vals = pd.keywords.get(fname, ())
            ords = sorted(ord_of[v] for v in set(vals))
            for o in ords:
                flat_ords.append(o)
                flat_docs.append(doc_i)
            if ords:
                min_ord[doc_i] = ords[0]
            starts[doc_i + 1] = len(flat_ords)
        keyword_cols[fname] = KeywordColumn(
            fname, vocab, starts, np.asarray(flat_ords, dtype=np.int32),
            np.asarray(flat_docs, dtype=np.int32), min_ord)

    for fname in geo_fields:
        lat = np.zeros(ndocs, dtype=np.float32)
        lon = np.zeros(ndocs, dtype=np.float32)
        present = np.zeros(ndocs, dtype=bool)
        for doc_i, pd in enumerate(parsed_docs):
            vals = pd.geos.get(fname)
            if vals:
                lat[doc_i], lon[doc_i] = vals[0]
                present[doc_i] = True
        geo_cols[fname] = GeoColumn(fname, lat, lon, present)

    vector_cols: Dict[str, VectorColumn] = {}
    for fname in vec_fields:
        ft = mappings.resolve_field(fname)
        dims = next(len(pd.vectors[fname]) for pd in parsed_docs
                    if fname in pd.vectors)
        values = np.zeros((ndocs, dims), np.float32)
        present = np.zeros(ndocs, bool)
        for doc_i, pd in enumerate(parsed_docs):
            vec = pd.vectors.get(fname)
            if vec is not None:
                values[doc_i] = vec
                present[doc_i] = True
        vector_cols[fname] = VectorColumn(
            fname, values, present,
            ft.vector_similarity if ft is not None else "cosine",
            method=ft.vector_method if ft is not None else None)

    shape_cols: Dict[str, ShapeColumn] = {}
    shape_fields = {f for pd in parsed_docs for f in pd.shapes}
    for fname in shape_fields:
        specs: list = [None] * ndocs
        minx = np.full(ndocs, np.inf)
        miny = np.full(ndocs, np.inf)
        maxx = np.full(ndocs, -np.inf)
        maxy = np.full(ndocs, -np.inf)
        present = np.zeros(ndocs, bool)
        for doc_i, pd in enumerate(parsed_docs):
            vals = pd.shapes.get(fname)  # [(spec, bbox)] from mapping parse
            if not vals:
                continue
            specs[doc_i] = [sp for sp, _bx in vals]
            present[doc_i] = True
            for _sp, bx in vals:
                minx[doc_i] = min(minx[doc_i], bx[0])
                miny[doc_i] = min(miny[doc_i], bx[1])
                maxx[doc_i] = max(maxx[doc_i], bx[2])
                maxy[doc_i] = max(maxy[doc_i], bx[3])
        shape_cols[fname] = ShapeColumn(fname, specs, minx, miny, maxx, maxy,
                                        present)

    # ---- nested blocks: child docs become their own CSR segment ----
    nested_paths = {p for pd in parsed_docs for p in pd.nested}
    nested: Dict[str, NestedBlock] = {}
    for npath in sorted(nested_paths):
        child_docs: List[Any] = []
        parent_of: List[int] = []
        for doc_i, pd in enumerate(parsed_docs):
            for child in pd.nested.get(npath, ()):
                child_docs.append(child)
                parent_of.append(doc_i)
        child_seg = build_segment(f"{name}/{npath}", child_docs, mappings,
                                  with_positions=with_positions)
        nested[npath] = NestedBlock(child_seg,
                                    np.asarray(parent_of, dtype=np.int32))

    seq = np.asarray(seq_nos, dtype=np.int64) if seq_nos is not None else None
    seg = Segment(name, ndocs, postings, numeric_cols, keyword_cols, geo_cols,
                  doc_lens, text_stats, ids, sources, seq_nos=seq,
                  vector_cols=vector_cols, nested=nested,
                  shape_cols=shape_cols, stored_vals=stored_vals)
    if default_codec_version() >= CODEC_V2:
        # codec v2: eager quantized impacts + block-max sidecar per
        # text-scored field (nested children recurse in build_impacts),
        # plus FEATURE planes for rank_features/sparse_vector fields
        # whose mapping opted into index_impacts (learned-sparse on the
        # impact ladder, docs/HYBRID.md)
        _t_q = time.perf_counter()
        seg.build_impacts(feature_fields=feature_impact_fields(
            mappings, feat_fields))
        note_stage("quantize", time.perf_counter() - _t_q)
    # term_vector=with_positions_offsets fields: per-doc (term, pos, start,
    # end) for the FVH path (host-only, like _source)
    seg.term_vectors = term_vectors
    return seg


# ---------------------------------------------------------------------
# streaming segment build (chunked posting accumulation, spill-and-merge)
# ---------------------------------------------------------------------
#
# The in-memory build (`build_segment` -> `pack_postings`) flattens the
# WHOLE doc buffer's token stream into Python lists before packing: at
# north-star scale (1M-8.8M docs, ~56 tokens/doc) that is hundreds of
# millions of Python string references — tens of GB of transient host
# memory for a segment whose final CSR arrays are ~1 GB. The streaming
# builder bounds the transient: docs are packed in fixed-size CHUNKS
# (each chunk through the same `pack_postings` native/python packer),
# every chunk's CSR + doc-value planes SPILL to disk, and `finish()`
# merges the sorted chunk runs into the final arrays with a vectorized
# run-scatter — no global sort, because chunk doc ranges are disjoint
# and ascending, so per-term concatenation in chunk order IS (term, doc)
# order. Peak host memory ~= final arrays + one chunk.
#
# Output is BIT-IDENTICAL to `build_segment` on the same docs
# (tests/test_stream_build.py pins it array-for-array): same vocab
# union, same CSR layout, same tf/position values, same doc-value
# columns, same text stats — and therefore the same codec-v2 impact
# planes, since those derive from (tf, dl, avgdl) alone.
#
# Scope: the streaming-eligible families are text/keyword-ish postings,
# numeric / keyword / geo / vector doc values and doc lengths — the
# north-star corpus shape. Docs carrying nested blocks, geo shapes,
# term-vector offsets or rank-features raise: those buffers are
# host-object-heavy either way, and the refresh path routes them to the
# in-memory build (`Engine.refresh` checks eligibility first).


def stream_eligible(parsed_docs) -> bool:
    """True when every doc uses only streaming-supported field families."""
    return not any(pd.nested or pd.shapes or pd.offsets or pd.features
                   for pd in parsed_docs if pd is not None)


class StreamingSegmentBuilder:
    """Bounded-memory segment construction: `add()` docs, `finish()` the
    Segment. One chunk of parsed docs is resident at a time; chunk CSRs
    spill to `spill_dir` (a private temp dir by default)."""

    def __init__(self, name: str, mappings: Mappings,
                 chunk_docs: int = 8192, spill_dir: Optional[str] = None,
                 with_positions: bool = True):
        import tempfile
        self.name = name
        self.mappings = mappings
        self.chunk_docs = max(int(chunk_docs), 1)
        self.with_positions = with_positions
        self._own_dir = spill_dir is None
        self._dir = spill_dir or tempfile.mkdtemp(prefix="ostpu_stream_")
        os.makedirs(self._dir, exist_ok=True)
        self._chunk: list = []
        self._chunks: list = []      # per-chunk meta dicts
        self._ndocs = 0
        self.ids: List[str] = []
        self.sources: List[dict] = []
        self._stored: list = []
        self._any_stored = False
        self._text_stats: Dict[str, TextFieldStats] = {}
        self._vec_sim: Dict[str, tuple] = {}
        self._npz_cache: Dict[int, Any] = {}
        self._finished = False

    # ---------------- ingest ----------------

    def add(self, parsed) -> None:
        if parsed.nested or parsed.shapes or parsed.offsets \
                or parsed.features:
            raise ValueError(
                "streaming build supports text/numeric/keyword/geo/vector "
                "families only; nested/shape/term_vector/feature docs take "
                "the in-memory build (see Engine.refresh eligibility gate)")
        self._chunk.append(parsed)
        if len(self._chunk) >= self.chunk_docs:
            self._flush_chunk()

    def add_many(self, parsed_iter) -> None:
        for pd in parsed_iter:
            self.add(pd)

    @property
    def ndocs(self) -> int:
        return self._ndocs + len(self._chunk)

    def _flush_chunk(self) -> None:
        docs = self._chunk
        self._chunk = []
        if not docs:
            return
        _t_spill = time.perf_counter()
        base = self._ndocs
        n = len(docs)
        self._ndocs += n
        arrays: Dict[str, np.ndarray] = {}
        meta = {"base": base, "n": n, "post": {}, "num": {}, "kw": {},
                "geo": [], "vec": {}, "dl": []}

        src_on = getattr(self.mappings, "source_enabled", True)
        for pd in docs:
            self.ids.append(pd.doc_id)
            self.sources.append(pd.source if src_on else {})
            sv = dict(pd.stored) if pd.stored else None
            self._any_stored = self._any_stored or bool(sv)
            self._stored.append(sv)

        # ---- text stats + per-chunk doc lengths (mirrors build_segment) --
        dl_f: Dict[str, np.ndarray] = {}
        for di, pd in enumerate(docs):
            for fname, terms in pd.terms.items():
                ft = self.mappings.resolve_field(fname)
                if ft is not None and ft.type == "text":
                    st = self._text_stats.setdefault(fname,
                                                     TextFieldStats())
                    st.doc_count += 1
                    st.sum_dl += len(terms)
                    dl = dl_f.setdefault(fname, np.zeros(n, np.int64))
                    dl[di] = len(terms)
        for fname, dl in dl_f.items():
            arrays[f"dl__{len(meta['dl'])}"] = dl
            meta["dl"].append(fname)

        # ---- postings: one packer run per chunk ----
        for fi, (fname, pb) in enumerate(
                sorted(pack_postings(docs, self.with_positions).items())):
            key = f"post__{fi}"
            arrays[f"{key}__starts"] = pb.starts
            arrays[f"{key}__doc_ids"] = pb.doc_ids
            arrays[f"{key}__tfs"] = pb.tfs
            positional = pb.pos_starts is not None
            if positional:
                arrays[f"{key}__pos_starts"] = pb.pos_starts
                arrays[f"{key}__positions"] = pb.positions
            meta["post"][fname] = {"i": fi, "vocab": pb.vocab,
                                   "positional": positional}

        # ---- doc values ----
        num_fields = {f for pd in docs for f in pd.numerics}
        for fi, fname in enumerate(sorted(num_fields)):
            kind = _numeric_kind(self.mappings, fname)
            dtype = np.float64 if kind == "float" else np.int64
            values = np.zeros(n, dtype=dtype)
            present = np.zeros(n, dtype=bool)
            for di, pd in enumerate(docs):
                vals = pd.numerics.get(fname)
                if vals:
                    values[di] = vals[0]
                    present[di] = True
            arrays[f"num__{fi}__values"] = values
            arrays[f"num__{fi}__present"] = present
            meta["num"][fname] = {"i": fi, "kind": kind}

        kw_fields = {f for pd in docs for f in pd.keywords}
        for fi, fname in enumerate(sorted(kw_fields)):
            value_set = set()
            for pd in docs:
                value_set.update(pd.keywords.get(fname, ()))
            vocab = sorted(value_set)
            ord_of = {v: i for i, v in enumerate(vocab)}
            starts = np.zeros(n + 1, dtype=np.int64)
            flat_ords: List[int] = []
            flat_docs: List[int] = []
            min_ord = np.full(n, -1, dtype=np.int32)
            for di, pd in enumerate(docs):
                vals = pd.keywords.get(fname, ())
                ords = sorted(ord_of[v] for v in set(vals))
                for o in ords:
                    flat_ords.append(o)
                    flat_docs.append(di)
                if ords:
                    min_ord[di] = ords[0]
                starts[di + 1] = len(flat_ords)
            arrays[f"kw__{fi}__starts"] = starts
            arrays[f"kw__{fi}__ords"] = np.asarray(flat_ords, np.int32)
            arrays[f"kw__{fi}__docs"] = np.asarray(flat_docs, np.int32)
            arrays[f"kw__{fi}__min_ord"] = min_ord
            meta["kw"][fname] = {"i": fi, "vocab": vocab}

        geo_fields = {f for pd in docs for f in pd.geos}
        for fi, fname in enumerate(sorted(geo_fields)):
            lat = np.zeros(n, dtype=np.float32)
            lon = np.zeros(n, dtype=np.float32)
            present = np.zeros(n, dtype=bool)
            for di, pd in enumerate(docs):
                vals = pd.geos.get(fname)
                if vals:
                    lat[di], lon[di] = vals[0]
                    present[di] = True
            arrays[f"geo__{fi}__lat"] = lat
            arrays[f"geo__{fi}__lon"] = lon
            arrays[f"geo__{fi}__present"] = present
            meta["geo"].append(fname)

        vec_fields = {f for pd in docs for f in pd.vectors}
        for fi, fname in enumerate(sorted(vec_fields)):
            ft = self.mappings.resolve_field(fname)
            dims = next(len(pd.vectors[fname]) for pd in docs
                        if fname in pd.vectors)
            self._vec_sim.setdefault(fname, (
                dims,
                ft.vector_similarity if ft is not None else "cosine",
                ft.vector_method if ft is not None else None))
            values = np.zeros((n, dims), np.float32)
            present = np.zeros(n, bool)
            for di, pd in enumerate(docs):
                vec = pd.vectors.get(fname)
                if vec is not None:
                    values[di] = vec
                    present[di] = True
            arrays[f"vec__{fi}__values"] = values
            arrays[f"vec__{fi}__present"] = present
            meta["vec"][fname] = {"i": fi}

        np.savez(os.path.join(self._dir, f"chunk{len(self._chunks)}.npz"),
                 **arrays)
        self._chunks.append(meta)
        note_stage("spill", time.perf_counter() - _t_spill)

    # ---------------- merge ----------------

    # open .npz handles kept during finish(): each holds an OS file
    # descriptor, so cap well under common ulimits (an 8.8M-doc build is
    # ~1075 chunks); merge loops walk chunks in ascending order, so FIFO
    # eviction drops exactly the handles not needed soon
    _NPZ_CACHE_FDS = 64

    def _chunk_arrays(self, ci: int):
        # one open NpzFile per chunk while it is being visited: members
        # load lazily, but every np.load re-parses the zip central
        # directory — the merge loops visit each chunk up to 3x per field
        arrs = self._npz_cache.get(ci)
        if arrs is None:
            while len(self._npz_cache) >= self._NPZ_CACHE_FDS:
                old = next(iter(self._npz_cache))
                try:
                    self._npz_cache.pop(old).close()
                except Exception:
                    pass
            arrs = np.load(os.path.join(self._dir, f"chunk{ci}.npz"),
                           allow_pickle=False)
            self._npz_cache[ci] = arrs
        return arrs

    def _merge_postings_field(self, fname: str) -> PostingsBlock:
        """Spill-and-merge of one field's chunk CSR runs: union vocab,
        then a vectorized run-scatter per chunk. Chunk doc ranges are
        disjoint ascending, so filling runs in chunk order lands every
        row in (doc ascending) order — identical to the global pack."""
        from .merge import _ranges_gather

        chunks = [(ci, m["post"][fname]) for ci, m in
                  enumerate(self._chunks) if fname in m["post"]]
        vocab = sorted({t for _ci, pm in chunks for t in pm["vocab"]})
        new_row_of = {t: i for i, t in enumerate(vocab)}
        nterms = len(vocab)
        positional = self.with_positions
        lens_u = np.zeros(nterms, np.int64)
        row_maps = {}
        for ci, pm in chunks:
            rm = np.fromiter((new_row_of[t] for t in pm["vocab"]),
                             np.int64, count=len(pm["vocab"]))
            row_maps[ci] = rm
            arrs = self._chunk_arrays(ci)
            clens = np.diff(arrs[f"post__{pm['i']}__starts"])
            np.add.at(lens_u, rm, clens)
        starts = np.zeros(nterms + 1, np.int64)
        np.cumsum(lens_u, out=starts[1:])
        total = int(starts[-1])
        doc_ids = np.empty(total, np.int32)
        tfs = np.empty(total, np.float32)
        plens = np.zeros(total, np.int64) if positional else None
        filled = np.zeros(nterms, np.int64)
        dsts = {}
        for ci, pm in chunks:
            arrs = self._chunk_arrays(ci)
            key = f"post__{pm['i']}"
            cstarts = arrs[f"{key}__starts"]
            clens = np.diff(cstarts)
            rm = row_maps[ci]
            run_dst = starts[rm] + filled[rm]
            pc = int(cstarts[-1])
            dst = (np.repeat(run_dst, clens)
                   + np.arange(pc, dtype=np.int64)
                   - np.repeat(cstarts[:-1], clens))
            base = self._chunks[ci]["base"]
            doc_ids[dst] = arrs[f"{key}__doc_ids"] + np.int32(base)
            tfs[dst] = arrs[f"{key}__tfs"]
            if positional:
                plens[dst] = np.diff(arrs[f"{key}__pos_starts"])
            filled[rm] += clens
            dsts[ci] = dst
        pos_starts = positions = None
        if positional:
            pos_starts = np.zeros(total + 1, np.int64)
            np.cumsum(plens, out=pos_starts[1:])
            positions = np.empty(int(pos_starts[-1]), np.int32)
            for ci, pm in chunks:
                arrs = self._chunk_arrays(ci)
                key = f"post__{pm['i']}"
                dst = dsts[ci]
                cplens = np.diff(arrs[f"{key}__pos_starts"])
                idx = _ranges_gather(pos_starts[:-1][dst], cplens)
                positions[idx] = arrs[f"{key}__positions"]
        return PostingsBlock(fname, vocab, new_row_of, starts, doc_ids,
                             tfs, pos_starts, positions)

    def finish(self, seq_nos: Optional[List[int]] = None) -> Segment:
        assert not self._finished
        self._finished = True
        self._flush_chunk()
        _t_merge = time.perf_counter()
        ndocs = self._ndocs
        try:
            post_fields = sorted({f for m in self._chunks
                                  for f in m["post"]})
            postings = {f: self._merge_postings_field(f)
                        for f in post_fields}

            numeric_cols: Dict[str, NumericColumn] = {}
            for f in sorted({f for m in self._chunks for f in m["num"]}):
                kind = next(m["num"][f]["kind"] for m in self._chunks
                            if f in m["num"])
                dtype = np.float64 if kind == "float" else np.int64
                values = np.zeros(ndocs, dtype=dtype)
                present = np.zeros(ndocs, dtype=bool)
                for ci, m in enumerate(self._chunks):
                    nm = m["num"].get(f)
                    if nm is None:
                        continue
                    arrs = self._chunk_arrays(ci)
                    sl = slice(m["base"], m["base"] + m["n"])
                    values[sl] = arrs[f"num__{nm['i']}__values"]
                    present[sl] = arrs[f"num__{nm['i']}__present"]
                numeric_cols[f] = NumericColumn(f, kind, values, present)

            keyword_cols: Dict[str, KeywordColumn] = {}
            for f in sorted({f for m in self._chunks for f in m["kw"]}):
                vocab = sorted({v for m in self._chunks
                                if f in m["kw"]
                                for v in m["kw"][f]["vocab"]})
                ord_of = {v: i for i, v in enumerate(vocab)}
                starts = np.zeros(ndocs + 1, np.int64)
                ord_parts, doc_parts = [], []
                min_ord = np.full(ndocs, -1, np.int32)
                counts = np.zeros(ndocs, np.int64)
                for ci, m in enumerate(self._chunks):
                    km = m["kw"].get(f)
                    if km is None:
                        continue
                    arrs = self._chunk_arrays(ci)
                    remap = np.fromiter(
                        (ord_of[v] for v in km["vocab"]), np.int64,
                        count=len(km["vocab"]))
                    cords = arrs[f"kw__{km['i']}__ords"]
                    cdocs = arrs[f"kw__{km['i']}__docs"]
                    cstarts = arrs[f"kw__{km['i']}__starts"]
                    cmin = arrs[f"kw__{km['i']}__min_ord"]
                    base = m["base"]
                    # monotone remap keeps per-doc ord order + min identity
                    ord_parts.append(remap[cords].astype(np.int32)
                                     if len(cords) else
                                     np.empty(0, np.int32))
                    doc_parts.append((cdocs + np.int32(base)))
                    counts[base: base + m["n"]] = np.diff(cstarts)
                    sl = min_ord[base: base + m["n"]]
                    sel = cmin >= 0
                    sl[sel] = remap[cmin[sel]].astype(np.int32)
                np.cumsum(counts, out=starts[1:])
                ords = (np.concatenate(ord_parts) if ord_parts
                        else np.empty(0, np.int32))
                docs_flat = (np.concatenate(doc_parts) if doc_parts
                             else np.empty(0, np.int32))
                keyword_cols[f] = KeywordColumn(f, vocab, starts,
                                                ords.astype(np.int32),
                                                docs_flat.astype(np.int32),
                                                min_ord)

            geo_cols: Dict[str, GeoColumn] = {}
            for f in sorted({f for m in self._chunks for f in m["geo"]}):
                lat = np.zeros(ndocs, np.float32)
                lon = np.zeros(ndocs, np.float32)
                present = np.zeros(ndocs, bool)
                for ci, m in enumerate(self._chunks):
                    if f not in m["geo"]:
                        continue
                    fi = m["geo"].index(f)
                    arrs = self._chunk_arrays(ci)
                    sl = slice(m["base"], m["base"] + m["n"])
                    lat[sl] = arrs[f"geo__{fi}__lat"]
                    lon[sl] = arrs[f"geo__{fi}__lon"]
                    present[sl] = arrs[f"geo__{fi}__present"]
                geo_cols[f] = GeoColumn(f, lat, lon, present)

            vector_cols: Dict[str, VectorColumn] = {}
            for f in sorted({f for m in self._chunks for f in m["vec"]}):
                dims, sim, method = self._vec_sim[f]
                values = np.zeros((ndocs, dims), np.float32)
                present = np.zeros(ndocs, bool)
                for ci, m in enumerate(self._chunks):
                    vm = m["vec"].get(f)
                    if vm is None:
                        continue
                    arrs = self._chunk_arrays(ci)
                    sl = slice(m["base"], m["base"] + m["n"])
                    values[sl] = arrs[f"vec__{vm['i']}__values"]
                    present[sl] = arrs[f"vec__{vm['i']}__present"]
                vector_cols[f] = VectorColumn(f, values, present, sim,
                                              method=method)

            doc_lens: Dict[str, np.ndarray] = {}
            for f in sorted({f for m in self._chunks for f in m["dl"]}):
                dl = np.zeros(ndocs, np.int64)
                for ci, m in enumerate(self._chunks):
                    if f not in m["dl"]:
                        continue
                    arrs = self._chunk_arrays(ci)
                    fi = m["dl"].index(f)
                    dl[m["base"]: m["base"] + m["n"]] = arrs[f"dl__{fi}"]
                doc_lens[f] = dl

            seq = (np.asarray(seq_nos, dtype=np.int64)
                   if seq_nos is not None else None)
            seg = Segment(self.name, ndocs, postings, numeric_cols,
                          keyword_cols, geo_cols, doc_lens,
                          self._text_stats, self.ids, self.sources,
                          seq_nos=seq, vector_cols=vector_cols,
                          stored_vals=(self._stored if self._any_stored
                                       else None))
            note_stage("chunk_merge", time.perf_counter() - _t_merge)
            if default_codec_version() >= CODEC_V2:
                # no feature_fields here BY INVARIANT: docs carrying
                # rank_features are not stream-eligible
                # (`stream_eligible` rejects pd.features), so the
                # refresh path routes them to `build_segment`, which
                # derives the index_impacts opt-in from the mappings.
                # If streaming ever learns feature postings, thread
                # `feature_impact_fields(self.mappings, ...)` through
                # here or big-buffer refreshes silently lose the plane
                # (and merges of such segments lose the opt-in forever).
                _t_q = time.perf_counter()
                seg.build_impacts()
                note_stage("quantize", time.perf_counter() - _t_q)
            seg.term_vectors = None
            return seg
        finally:
            self._cleanup()

    def _cleanup(self) -> None:
        import shutil
        for arrs in self._npz_cache.values():
            try:
                arrs.close()
            except Exception:
                pass
        self._npz_cache = {}
        if self._own_dir:
            shutil.rmtree(self._dir, ignore_errors=True)
        else:
            # remove by directory listing, not by self._chunks count: an
            # aborted build (exception in add/_flush_chunk) may have
            # spilled more chunk files than _chunks records, and a
            # persistent engine spill_dir would otherwise retain them
            # forever (each failed refresh can strand a buffer's worth)
            for fn in os.listdir(self._dir):
                if fn.startswith("chunk") and fn.endswith(".npz"):
                    try:
                        os.remove(os.path.join(self._dir, fn))
                    except OSError:
                        pass


def build_segment_streaming(name: str, parsed_docs, mappings: Mappings,
                            seq_nos: Optional[List[int]] = None,
                            chunk_docs: int = 8192,
                            spill_dir: Optional[str] = None,
                            with_positions: bool = True) -> Segment:
    """Streaming counterpart of `build_segment` (same output, bounded
    transient memory): accepts any iterable of parsed docs."""
    b = StreamingSegmentBuilder(name, mappings, chunk_docs=chunk_docs,
                                spill_dir=spill_dir,
                                with_positions=with_positions)
    try:
        b.add_many(parsed_docs)
    except BaseException:
        # finish() cleans up after itself; a failure BEFORE finish must
        # too, or a persistent spill_dir (Engine.refresh) strands every
        # already-spilled chunk of the aborted buffer on disk
        b._cleanup()
        raise
    return b.finish(seq_nos=seq_nos)
