from .engine import Engine, VersionConflictError
from .mappings import FieldType, Mappings, ParsedDocument
from .segment import Segment, build_segment

__all__ = ["Engine", "VersionConflictError", "Mappings", "FieldType",
           "ParsedDocument", "Segment", "build_segment"]
