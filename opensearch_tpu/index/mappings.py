"""Field mappings and document parsing. Analog of reference
`server/src/main/java/org/opensearch/index/mapper/` (MapperService,
DocumentMapper, TextFieldMapper, KeywordFieldMapper, NumberFieldMapper,
DateFieldMapper, BooleanFieldMapper, IpFieldMapper, GeoPointFieldMapper,
ObjectMapper, FieldAliasMapper, dynamic templates).

Documents are parsed on the host into per-field term lists (indexed fields)
and doc-value scalars (columnar fields); the device only ever sees integer
term rows and numeric columns.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress
import numbers
import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import AnalysisRegistry, Analyzer

TEXT_TYPES = {"text", "match_only_text", "search_as_you_type",
              "annotated_text"}
KEYWORD_TYPES = {"keyword", "ip", "constant_keyword", "flat_object",
                 "icu_collation_keyword"}
INT_TYPES = {"long", "integer", "short", "byte", "date", "boolean",
             "unsigned_long", "token_count"}
FLOAT_TYPES = {"double", "float", "half_float", "rank_feature",
               "scaled_float"}
NUMERIC_TYPES = INT_TYPES | FLOAT_TYPES
# range family (reference RangeFieldMapper): stored as closed [lo, hi]
# interval columns `field#lo` / `field#hi` in the member type's column
# representation; queried with relation intersects/within/contains
RANGE_TYPES = {"integer_range", "long_range", "float_range", "double_range",
               "date_range", "ip_range"}
RANGE_MEMBER = {"integer_range": "integer", "long_range": "long",
                "float_range": "float", "double_range": "double",
                "date_range": "date", "ip_range": "ip"}
# unsigned_long stores order-preserving BIASED i64 (v - 2^63) so 64-bit
# compares/sorts stay exact; the device f32 view and fetch unbias
# (reference UnsignedLongFieldMapper shifts the same way)
U64_BIAS = 1 << 63
GEO_TYPES = {"geo_point"}
SHAPE_TYPES = {"geo_shape"}
VECTOR_TYPES = {"dense_vector", "knn_vector"}
# feature-weight CSR fields (reference mapper-extras RankFeaturesFieldMapper;
# sparse_vector is the same storage with learned-sparse token weights)
FEATURE_TYPES = {"rank_features", "sparse_vector"}


@dataclass
class FieldType:
    name: str
    type: str
    analyzer: str = "standard"
    search_analyzer: Optional[str] = None
    normalizer: Optional[str] = None
    index: bool = True
    doc_values: bool = True
    store: bool = False
    null_value: Any = None
    ignore_above: Optional[int] = None
    copy_to: List[str] = dc_field(default_factory=list)
    date_format: Optional[str] = None
    boost: float = 1.0
    dims: int = 0                       # dense_vector dimension
    vector_similarity: str = "cosine"   # cosine | dot_product | l2_norm
    # ANN method (reference k-NN plugin `method` / ES `index_options`):
    # normalized to {"name": "ivf", "nlist": int|None, "nprobe": int|None};
    # None = exact brute-force scan (the default)
    vector_method: Optional[dict] = None
    # join field (reference modules/parent-join ParentJoinFieldMapper):
    # {"parent_relation": ["child_relation", ...]}
    relations: Dict[str, List[str]] = dc_field(default_factory=dict)
    # rank_feature(s): False flips the scoring functions (reference
    # RankFeatureFieldMapper positive_score_impact)
    positive_score_impact: bool = True
    # text fields keep norms (doc length) unless disabled; keyword fields never
    norms: bool = True
    subfields: Dict[str, "FieldType"] = dc_field(default_factory=dict)
    # scaled_float (mapper-extras ScaledFloatFieldMapper): values quantize
    # to round(v * scaling_factor) / scaling_factor
    scaling_factor: Optional[float] = None
    # constant_keyword (ConstantKeywordFieldMapper): the index-wide value
    # (from the mapping, or adopted from the first document that sets it)
    const_value: Optional[str] = None
    # synthetic flat_object leaf (FlatObjectFieldMapper ._valueAndPath):
    # query terms become "<flat_prefix>=<value>" against `<root>#paths`
    flat_prefix: Optional[str] = None
    # term_vector: "with_positions_offsets" persists per-doc (term, pos,
    # start, end) for the real FastVectorHighlighter path
    term_vector: str = "no"
    # rank_features/sparse_vector opt-in: build a codec-v2 FEATURE
    # impact plane (quantized model-assigned weights + block-max
    # sidecar) so neural_sparse serves through the impact ladder
    # (search/impactpath.py, docs/HYBRID.md)
    index_impacts: bool = False

    @property
    def is_indexed_terms(self) -> bool:
        return self.index and (self.type in TEXT_TYPES or self.type in KEYWORD_TYPES)

    @property
    def has_norms(self) -> bool:
        return self.type in TEXT_TYPES and self.norms and \
            self.type != "match_only_text"


_ANNOT_RE = re.compile(r"\[([^\]]*)\]\(([^)]+)\)")


def parse_annotated_text(raw: str):
    """-> (plain_text, [(char_start, char_end, [annotation values])]).

    Markup follows the reference plugin (mapper-annotated-text): the covered
    text appears in the plain stream; `&`-separated, URL-encoded annotation
    values attach to its character span."""
    import urllib.parse as _up
    plain_parts = []
    spans = []
    pos = 0
    last = 0
    for m in _ANNOT_RE.finditer(raw):
        plain_parts.append(raw[last:m.start()])
        pos += m.start() - last
        text = m.group(1)
        anns = [_up.unquote(a) for a in m.group(2).split("&") if a]
        spans.append((pos, pos + len(text), anns))
        plain_parts.append(text)
        pos += len(text)
        last = m.end()
    plain_parts.append(raw[last:])
    return "".join(plain_parts), spans


def _parse_date(value: Any, fmt: Optional[str]) -> int:
    """Parse a date into epoch millis (reference DateFieldMapper; default
    format `strict_date_optional_time||epoch_millis`)."""
    if isinstance(value, bool):
        raise ValueError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, numbers.Number):
        return int(value)
    s = str(value).strip()
    if fmt == "epoch_second":
        return int(float(s) * 1000)
    if s.isdigit() or (s[:1] == "-" and s[1:].isdigit()):
        return int(s)
    iso = s.replace("Z", "+00:00")
    try:
        dt = _dt.datetime.fromisoformat(iso)
    except ValueError:
        for f in ("%Y/%m/%d", "%Y/%m/%d %H:%M:%S", "%d-%m-%Y", "%m/%d/%Y"):
            try:
                dt = _dt.datetime.strptime(s, f)
                break
            except ValueError:
                continue
        else:
            raise ValueError(f"failed to parse date field [{s}]")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


def _ip_to_int(value: str) -> int:
    """IPs index as integers (v4 mapped into v6 space like Lucene InetAddressPoint)."""
    ip = ipaddress.ip_address(value)
    if isinstance(ip, ipaddress.IPv4Address):
        ip = ipaddress.IPv6Address(f"::ffff:{value}")
    return int(ip)


def coerce_value(ft: FieldType, value: Any):
    """Coerce a raw JSON value to the column representation: ints for the long
    family (dates→millis, bool→0/1, ip→int), floats for the float family."""
    t = ft.type
    if t == "date":
        return _parse_date(value, ft.date_format)
    if t == "boolean":
        if isinstance(value, str):
            if value in ("true", "True"):
                return 1
            if value in ("false", "False", ""):
                return 0
            raise ValueError(f"cannot parse boolean [{value}]")
        return 1 if bool(value) else 0
    if t == "ip":
        return _ip_to_int(str(value))
    if t == "unsigned_long":
        iv = int(value)
        if not 0 <= iv < (1 << 64):
            raise ValueError(
                f"value [{value}] out of range for field type [unsigned_long]")
        return iv - U64_BIAS
    if t in INT_TYPES:
        iv = int(value)
        limits = {"long": 63, "integer": 31, "short": 15, "byte": 7}
        bits = limits.get(t, 63)
        if not (-(1 << bits)) <= iv < (1 << bits):
            raise ValueError(f"value [{value}] out of range for field type [{t}]")
        return iv
    if t == "scaled_float":
        sf = ft.scaling_factor or 1.0
        return round(float(value) * sf) / sf
    if t in FLOAT_TYPES:
        fv = float(value)
        if t == "rank_feature" and fv <= 0:
            raise ValueError(
                f"[rank_feature] fields must hold positive values, got [{fv}]")
        return fv
    raise ValueError(f"cannot coerce for type [{t}]")


@dataclass
class ParsedDocument:
    """Index-ready view of one document (analog of reference ParsedDocument)."""

    doc_id: str
    source: dict
    routing: Optional[str]
    # field -> list of terms (text: analyzed tokens incl. duplicates for tf;
    # keyword: normalized exact values)
    terms: Dict[str, List[str]] = dc_field(default_factory=dict)
    # field -> list of (term, position) for positional indexes
    positions: Dict[str, List[Tuple[str, int]]] = dc_field(default_factory=dict)
    # field -> per-VALUE lists of (term, position, start_offset,
    # end_offset) for term_vector=with_positions_offsets fields (FVH);
    # offsets are relative to their own value string
    offsets: Dict[str, List[List[Tuple[str, int, int, int]]]] = dc_field(default_factory=dict)
    # field -> raw values for store=true fields (reference stored fields)
    stored: Dict[str, list] = dc_field(default_factory=dict)
    # field -> list of numeric values (column stores the first; extra values
    # still participate in term-style matching for the long family)
    numerics: Dict[str, List[Any]] = dc_field(default_factory=dict)
    # field -> list of keyword strings for doc values (terms agg / sort)
    keywords: Dict[str, List[str]] = dc_field(default_factory=dict)
    # field -> list of (lat, lon)
    geos: Dict[str, List[Tuple[float, float]]] = dc_field(default_factory=dict)
    # field -> list of geo_shape specs (GeoJSON dict / WKT string, validated)
    shapes: Dict[str, List[Any]] = dc_field(default_factory=dict)
    # field -> vector (one per doc)
    vectors: Dict[str, List[float]] = dc_field(default_factory=dict)
    # nested path -> child ParsedDocuments (block-join children; reference
    # NestedObjectMapper creates separate Lucene docs in the parent's block)
    nested: Dict[str, List["ParsedDocument"]] = dc_field(default_factory=dict)
    # field -> {feature: weight} (rank_features / sparse_vector)
    features: Dict[str, Dict[str, float]] = dc_field(default_factory=dict)


class Mappings:
    """Per-index mappings with dynamic mapping (reference MapperService).

    Construction takes the `{"properties": {...}}` mapping dict; unseen fields
    encountered at parse time are dynamically mapped (string→text+`.keyword`
    subfield, int→long, float→double, bool→boolean, dict→object) exactly like
    the reference's default dynamic rules.
    """

    def __init__(self, mapping: dict | None = None, analysis: AnalysisRegistry | None = None,
                 dynamic: bool | str = True):
        self.analysis = analysis or AnalysisRegistry()
        self.fields: Dict[str, FieldType] = {}
        self.aliases: Dict[str, str] = {}
        self.nested_paths: set = set()
        self.join_field: Optional[str] = None  # at most one per index (like reference)
        self.dynamic = dynamic
        self.dynamic_templates: List[dict] = []
        self.derived: Dict[str, Any] = {}   # name -> DerivedField
        self.star_trees: List[Any] = []     # StarTreeConfig (search/startree)
        self._meta: dict = {}
        # reference SourceFieldMapper: `"_source": {"enabled": false}` stops
        # persisting _source in segments (store=true fields remain fetchable
        # via stored_fields; update/reindex lose their input, as upstream)
        self.source_enabled = True
        # plugins/mapper-size SizeFieldMapper: `"_size": {"enabled": true}`
        # indexes the byte length of _source as numeric doc values
        self.size_enabled = False
        if mapping:
            self.merge(mapping)

    # ---------------- mapping CRUD ----------------

    def merge(self, mapping: dict) -> None:
        if "dynamic" in mapping:
            self.dynamic = mapping["dynamic"]
        if "_meta" in mapping:
            self._meta.update(mapping["_meta"])
        if "_source" in mapping:
            self.source_enabled = bool(mapping["_source"].get("enabled", True))
        if "_size" in mapping:
            self.size_enabled = bool(mapping["_size"].get("enabled", False))
            if self.size_enabled and "_size" not in self.fields:
                self.fields["_size"] = FieldType(name="_size", type="long",
                                                 index=False)
        self.dynamic_templates.extend(mapping.get("dynamic_templates", []))
        self._merge_props(mapping.get("properties", {}), prefix="")
        if "derived" in mapping:
            # derived (runtime) fields: scripts evaluated per segment at
            # query time (search/derived.py; reference DerivedFieldMapper)
            from ..search.derived import check_conflicts, parse_defs
            defs = parse_defs(mapping["derived"])
            check_conflicts(self, defs)
            self.derived.update(defs)

    def _merge_props(self, props: dict, prefix: str) -> None:
        for name, cfg in props.items():
            path = f"{prefix}{name}"
            ftype = cfg.get("type", "object" if "properties" in cfg else "text")
            if ftype == "alias":
                self.aliases[path] = cfg["path"]
                continue
            if ftype in ("object", "nested"):
                if ftype == "nested":
                    self.nested_paths.add(path)
                self._merge_props(cfg.get("properties", {}), prefix=f"{path}.")
                continue
            if ftype == "star_tree":
                # composite pre-agg cube config (search/startree.py;
                # reference StarTreeMapper) — config-only, no doc values
                from ..search.startree import parse_config
                self.star_trees.append(parse_config(path, cfg))
                continue
            self.fields[path] = self._build_field(path, ftype, cfg)
            if ftype == "join":
                if self.join_field is not None and self.join_field != path:
                    raise ValueError(
                        f"only one [join] field can be defined per index, "
                        f"found [{self.join_field}] and [{path}]")
                self.join_field = path

    def _build_field(self, path: str, ftype: str, cfg: dict) -> FieldType:
        normalizer = cfg.get("normalizer")
        if ftype == "icu_collation_keyword":
            # reference ICUCollationKeywordFieldMapper
            # (plugins/analysis-icu): values index and doc-value as
            # collation SORT KEYS, so term queries / sorting / aggs all
            # operate in collation space. `language`/`country` accepted
            # for API parity; key construction is locale-independent
            # (strength cascade approximated; see unicode_plugins)
            strength = cfg.get("strength", "tertiary")
            if strength not in ("primary", "secondary", "tertiary"):
                raise ValueError(
                    f"[icu_collation_keyword] field [{path}]: unsupported "
                    f"strength [{strength}] (supported: primary, "
                    f"secondary, tertiary)")
            normalizer = f"_icu_collation:{strength}"
        ft = FieldType(
            name=path, type=ftype,
            analyzer=cfg.get("analyzer", "standard"),
            search_analyzer=cfg.get("search_analyzer"),
            normalizer=normalizer,
            index=cfg.get("index", True),
            doc_values=cfg.get("doc_values", True),
            store=cfg.get("store", False),
            null_value=cfg.get("null_value"),
            ignore_above=cfg.get("ignore_above"),
            copy_to=list(cfg.get("copy_to", []) if isinstance(cfg.get("copy_to", []), list)
                         else [cfg["copy_to"]]),
            date_format=cfg.get("format"),
            term_vector=cfg.get("term_vector", "no"),
            boost=cfg.get("boost", 1.0),
            norms=cfg.get("norms", True),
            dims=int(cfg.get("dims", cfg.get("dimension", 0))),
            vector_similarity=cfg.get("similarity",
                                      cfg.get("space_type", "cosine")),
        )
        if ftype in VECTOR_TYPES:
            method = cfg.get("method") or cfg.get("index_options")
            if method:
                name = method.get("name", method.get("type", "ivf"))
                if name not in ("ivf", "flat", "exact"):
                    raise ValueError(
                        f"unknown ANN method [{name}] for field [{path}] "
                        f"(supported: ivf, flat)")
                if name == "ivf":
                    p = method.get("parameters", method)
                    ft.vector_method = {
                        "name": "ivf",
                        "nlist": (int(p["nlist"]) if p.get("nlist") else None),
                        "nprobe": (int(p["nprobe"]) if p.get("nprobe")
                                   else None)}
        if ftype == "join":
            ft.relations = {p: (c if isinstance(c, list) else [c])
                            for p, c in cfg.get("relations", {}).items()}
        ft.positive_score_impact = bool(cfg.get("positive_score_impact", True))
        if "index_impacts" in cfg:
            if ftype not in FEATURE_TYPES:
                raise ValueError(
                    f"Field [{path}]: [index_impacts] only applies to "
                    f"rank_features/sparse_vector fields")
            ft.index_impacts = bool(cfg["index_impacts"])
        if ftype == "scaled_float":
            if "scaling_factor" not in cfg:
                raise ValueError(
                    f"Field [{path}] misses required parameter "
                    f"[scaling_factor]")
            ft.scaling_factor = float(cfg["scaling_factor"])
        if ftype == "constant_keyword":
            if cfg.get("value") is not None:
                ft.const_value = str(cfg["value"])
        if ftype == "search_as_you_type":
            # reference SearchAsYouTypeFieldMapper: main field + shingle
            # subfields + an edge-ngram prefix field for bool_prefix
            shingles = int(cfg.get("max_shingle_size", 3))
            self.analysis.ensure_sayt_chains(shingles)
            for n in range(2, shingles + 1):
                ft.subfields[f"_{n}gram"] = FieldType(
                    name=f"{path}._{n}gram", type="text",
                    analyzer=f"__sayt_{n}gram")
            ft.subfields["_index_prefix"] = FieldType(
                name=f"{path}._index_prefix", type="text",
                analyzer="__sayt_prefix",
                search_analyzer=cfg.get("analyzer", "standard"))
        for sub, subcfg in cfg.get("fields", {}).items():
            ft.subfields[sub] = self._build_field(f"{path}.{sub}", subcfg.get("type", "keyword"), subcfg)
        return ft

    def to_dict(self) -> dict:
        props: dict = {}
        for path, ft in self.fields.items():
            node = props
            parts = path.split(".")
            # reconstruct nested properties for object paths
            skip = False
            for p in parts[:-1]:
                if f"{'.'.join(parts[:parts.index(p)+1])}" in self.fields:
                    skip = True  # dotted subfield of a mapped field, not an object
                    break
                node = node.setdefault(p, {}).setdefault("properties", {})
            if skip:
                continue
            d: dict = {"type": ft.type}
            if ft.relations:
                d["relations"] = ft.relations
            if ft.type == "text" and ft.analyzer != "standard":
                d["analyzer"] = ft.analyzer
            if ft.type == "icu_collation_keyword":
                # round-trip the strength PARAM, not the internal
                # normalizer name (feeding the mapping back into create
                # must reproduce the same field)
                d["strength"] = (ft.normalizer or "_icu_collation:tertiary"
                                 ).split(":", 1)[1]
            elif ft.normalizer:
                d["normalizer"] = ft.normalizer
            if not ft.index:
                d["index"] = False
            if ft.subfields:
                d["fields"] = {s: {"type": sf.type} for s, sf in ft.subfields.items()}
            node[parts[-1]] = d
        for npath in sorted(self.nested_paths):
            parts = npath.split(".")
            node = props
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node.setdefault(parts[-1], {})["type"] = "nested"
        out = {"properties": props}
        if self.derived:
            out["derived"] = {
                n: {"type": d.type, "script": {"source": d.source},
                    **({"format": d.fmt} if d.fmt else {})}
                for n, d in self.derived.items()}
        if self._meta:
            out["_meta"] = self._meta
        if not self.source_enabled:
            out["_source"] = {"enabled": False}
        return out

    # ---------------- field resolution ----------------

    def resolve_field(self, name: str) -> Optional[FieldType]:
        name = self.aliases.get(name, name)
        ft = self.fields.get(name)
        if ft is not None:
            return ft
        # multi-field lookup: "title.keyword"
        if "." in name:
            parent, sub = name.rsplit(".", 1)
            parent = self.aliases.get(parent, parent)
            pft = self.fields.get(parent)
            if pft and sub in pft.subfields:
                return pft.subfields[sub]
            # flat_object leaf: "f.a.b" -> term "a.b=<v>" on "f#paths"
            # (reference FlatObjectFieldMapper ._valueAndPath field)
            parts = name.split(".")
            for i in range(1, len(parts)):
                root = ".".join(parts[:i])
                rft = self.fields.get(root)
                if rft is not None and rft.type == "flat_object":
                    sub_path = ".".join(parts[i:])
                    return FieldType(name=f"{root}#paths", type="keyword",
                                     flat_prefix=sub_path)
        df = self.derived.get(name)
        if df is not None:
            return FieldType(name=name, type=df.type, date_format=df.fmt)
        return None

    def index_analyzer(self, ft: FieldType) -> Analyzer:
        if ft.type in KEYWORD_TYPES:
            return self.analysis.normalizer(ft.normalizer)
        return self.analysis.get(ft.analyzer)

    def search_analyzer_for(self, ft: FieldType) -> Analyzer:
        if ft.type in KEYWORD_TYPES:
            return self.analysis.normalizer(ft.normalizer)
        return self.analysis.get(ft.search_analyzer or ft.analyzer)

    # ---------------- dynamic mapping ----------------

    def _dynamic_type(self, path: str, value: Any) -> Optional[FieldType]:
        for tmpl in self.dynamic_templates:
            rule = next(iter(tmpl.values()))
            match = rule.get("match", "*")
            import fnmatch
            if fnmatch.fnmatch(path.split(".")[-1], match):
                cfg = dict(rule.get("mapping", {}))
                return self._build_field(path, cfg.get("type", "text"), cfg)
        if isinstance(value, bool):
            return self._build_field(path, "boolean", {})
        if isinstance(value, int):
            return self._build_field(path, "long", {})
        if isinstance(value, float):
            return self._build_field(path, "double", {})
        if isinstance(value, str):
            # try date detection like reference's date_detection (ISO only)
            try:
                _dt.datetime.fromisoformat(value.replace("Z", "+00:00"))
                return self._build_field(path, "date", {})
            except ValueError:
                pass
            return self._build_field(path, "text",
                                     {"fields": {"keyword": {"type": "keyword",
                                                             "ignore_above": 256}}})
        return None

    # ---------------- document parsing ----------------

    def parse(self, doc_id: str, source: dict, routing: Optional[str] = None) -> ParsedDocument:
        parsed = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        self._parse_obj(source, "", parsed)
        # constant_keyword fields apply to EVERY document once a value is
        # known (reference ConstantKeywordFieldMapper)
        for ft in self.fields.values():
            if ft.type == "constant_keyword" and ft.const_value is not None:
                parsed.terms.setdefault(ft.name, []).append(ft.const_value)
                parsed.keywords.setdefault(ft.name, []).append(ft.const_value)
        if self.size_enabled:
            import json as _json
            parsed.numerics["_size"] = [len(_json.dumps(
                source, separators=(",", ":"), default=str).encode("utf-8"))]
        return parsed

    def _parse_obj(self, obj: dict, prefix: str, parsed: ParsedDocument) -> None:
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if path in self.nested_paths:
                # block-join children: each object indexes as its own child
                # doc (fields keep their full dotted path), attached to the
                # nearest enclosing doc — multi-level nested paths therefore
                # attach grandchildren to their child doc, and build_segment's
                # recursion gives every level its own block
                if value is None:
                    continue  # explicit null nested value == missing
                children = value if isinstance(value, list) else [value]
                bucket = parsed.nested.setdefault(path, [])
                for child_obj in children:
                    if child_obj is None:
                        continue
                    if not isinstance(child_obj, dict):
                        raise ValueError(
                            f"object mapping for [{path}] tried to parse a "
                            f"non-object value")
                    child = ParsedDocument(
                        doc_id=f"{parsed.doc_id}#{path}#{len(bucket)}",
                        source=child_obj, routing=None)
                    bucket.append(child)
                    self._parse_obj(child_obj, f"{path}.", child)
                continue
            if isinstance(value, dict):
                ft = self.resolve_field(path)
                if ft is not None and (ft.type in GEO_TYPES or ft.type in FEATURE_TYPES
                                       or ft.type in SHAPE_TYPES
                                       or ft.type in RANGE_TYPES
                                       or ft.type in ("join", "percolator",
                                                      "flat_object")):
                    self._index_value(ft, value, parsed)
                else:
                    self._parse_obj(value, f"{path}.", parsed)
                continue
            values = value if isinstance(value, list) else [value]
            if values and all(isinstance(v, dict) for v in values):
                lft = self.resolve_field(path)
                if lft is not None and lft.type in FEATURE_TYPES:
                    raise ValueError(
                        f"[{lft.type}] field [{path}] does not support arrays "
                        f"of feature objects")
                if lft is not None and (lft.type in SHAPE_TYPES
                                        or lft.type in GEO_TYPES
                                        or lft.type in RANGE_TYPES
                                        or lft.type == "flat_object"):
                    for v in values:
                        self._index_value(lft, v, parsed)
                    continue
                for v in values:
                    self._parse_obj(v, f"{path}.", parsed)
                continue
            ft = self.resolve_field(path)
            if ft is None:
                if self.dynamic in (False, "false"):
                    continue
                if self.dynamic == "strict":
                    raise ValueError(f"strict_dynamic_mapping_exception: [{path}] not allowed")
                sample = next((v for v in values if v is not None), None)
                if sample is None:
                    continue
                ft = self._dynamic_type(path, sample)
                if ft is None:
                    continue
                self.fields[path] = ft
            self._index_value(ft, value, parsed)

    def _index_value(self, ft: FieldType, value: Any, parsed: ParsedDocument) -> None:
        if (ft.type in GEO_TYPES and isinstance(value, list) and value
                and isinstance(value[0], numbers.Number)):
            value = [value]  # GeoJSON [lon, lat] is one point, not two values
        if ft.type in VECTOR_TYPES and isinstance(value, list):
            value = [value]  # the whole list is ONE vector value
        values = value if isinstance(value, list) else [value]
        for v in values:
            if v is None:
                v = ft.null_value
                if v is None:
                    continue
            self._index_single(ft, v, parsed)
        for sub in ft.subfields.values():
            self._index_value(sub, value, parsed)
        for target in ft.copy_to:
            tft = self.resolve_field(target)
            if tft is None:
                tft = self._dynamic_type(target, values[0] if values else "")
                if tft is None:
                    continue
                self.fields[target] = tft
            self._index_value(tft, value, parsed)

    def _index_single(self, ft: FieldType, v: Any, parsed: ParsedDocument) -> None:
        name = ft.name
        if ft.store:
            # stored fields keep the raw JSON value (reference StoredField)
            parsed.stored.setdefault(name, []).append(v)
        if ft.type == "percolator":
            # validate the stored query now and extract its pre-filter terms
            # (reference PercolatorFieldMapper + QueryAnalyzer); the query
            # itself lives in _source
            if not isinstance(v, dict):
                raise ValueError(f"percolator field [{name}] must hold a query object")
            from ..search.percolate import extract_index_terms
            from ..search.query_dsl import QueryParseError
            try:
                terms, always = extract_index_terms(v, self)
            except QueryParseError as e:
                raise ValueError(f"percolator query is invalid: {e}")
            if terms:
                parsed.keywords.setdefault(f"{name}#terms", []).extend(terms)
            if always:
                parsed.keywords.setdefault(f"{name}#flags", []).append("any")
            return
        if ft.type == "join":
            # reference ParentJoinFieldMapper: value is the relation name, or
            # {"name": ..., "parent": id} for child docs; children must carry
            # an explicit routing (same-shard requirement for the join)
            if isinstance(v, str):
                rel, parent = v, None
            elif isinstance(v, dict):
                rel, parent = v.get("name"), v.get("parent")
            else:
                raise ValueError(f"cannot parse join field value [{v}]")
            child_rels = {c for cs in ft.relations.values() for c in cs}
            if rel not in set(ft.relations) | child_rels:
                raise ValueError(f"unknown join name [{rel}] for field [{name}]")
            if rel in child_rels:
                if parent is None:
                    raise ValueError(
                        f"[parent] is missing for join field [{name}] "
                        f"child relation [{rel}]")
                if parsed.routing is None:
                    raise ValueError(
                        "[routing] is missing for a doc with a child join "
                        f"relation [{rel}]")
                parsed.terms.setdefault(f"{name}#parent", []).append(str(parent))
                parsed.keywords.setdefault(f"{name}#parent", []).append(str(parent))
            parsed.terms.setdefault(name, []).append(rel)
            parsed.keywords.setdefault(name, []).append(rel)
            return
        if ft.type == "murmur3":
            # plugins/mapper-murmur3 Murmur3FieldMapper: the value itself is
            # not indexed — its murmur3 hash lands in numeric doc values
            # (cardinality-agg fodder). The reference stores the first 64
            # bits of the x64_128 hash; this build uses the same x86_32
            # function the routing layer uses (documented divergence: both
            # are stable murmur3 variants, neither is queryable by value).
            from ..cluster.routing import murmur3_x86_32
            h = murmur3_x86_32(str(v).encode("utf-8"))
            parsed.numerics.setdefault(name, []).append(
                h - 0x100000000 if h >= 0x80000000 else h)
            return
        if ft.type in TEXT_TYPES:
            if ft.index:
                raw_text = str(v)
                annot_spans: list = []
                if ft.type == "annotated_text":
                    # plugins/mapper-annotated-text AnnotatedTextFieldMapper:
                    # inline [text](value1&value2) markup; the plain text is
                    # analyzed normally and each annotation value is injected
                    # as an un-analyzed term at the position of the first
                    # token it covers (phrase positions stay consistent)
                    raw_text, annot_spans = parse_annotated_text(raw_text)
                tokens = self.index_analyzer(ft).analyze(raw_text)
                tl = parsed.terms.setdefault(name, [])
                if ft.type == "match_only_text":
                    # no freqs, no norms, no positions (reference
                    # MatchOnlyTextFieldMapper): tf clamps to 1; phrases
                    # verify against _source at query time
                    seen = set(tl)
                    for t in tokens:
                        if t.text not in seen:
                            tl.append(t.text)
                            seen.add(t.text)
                    return
                pl = parsed.positions.setdefault(name, [])
                # position gap between values; max() not pl[-1] because
                # annotation terms append with the position of the token
                # they cover, which can be far below the value's extent
                base = max(p for _, p in pl) + 100 if pl else 0
                ol = None
                if "offsets" in ft.term_vector:
                    ol = []
                    parsed.offsets.setdefault(name, []).append(ol)
                for t in tokens:
                    tl.append(t.text)
                    pl.append((t.text, base + t.position))
                    if ol is not None:
                        ol.append((t.text, base + t.position,
                                   t.start_offset, t.end_offset))
                for (cs, ce, anns) in annot_spans:
                    # inject each annotation value as an exact term at the
                    # position (and offsets) of the first covered token
                    tok = next((t for t in tokens
                                if cs <= t.start_offset < ce), None)
                    at_pos = base + (tok.position if tok else 0)
                    for a in anns:
                        tl.append(a)
                        pl.append((a, at_pos))
                        if ol is not None and tok is not None:
                            ol.append((a, at_pos, tok.start_offset,
                                       tok.end_offset))
            return
        if ft.type == "binary":
            # base64 payload: stored/_source only, never indexed (reference
            # BinaryFieldMapper)
            return
        if ft.type == "token_count":
            tokens = self.analysis.get(ft.analyzer).analyze(str(v))
            parsed.numerics.setdefault(name, []).append(len(tokens))
            return
        if ft.type == "constant_keyword":
            s = str(v)
            if ft.const_value is None:
                ft.const_value = s     # first value fixes it (reference)
            elif s != ft.const_value:
                raise ValueError(
                    f"[constant_keyword] field [{name}] only accepts value "
                    f"[{ft.const_value}], got [{s}]")
            return                     # indexed for every doc in parse()
        if ft.type == "flat_object":
            # flatten leaves: root field gets every leaf value (searchable
            # + doc values), `name#paths` gets "path=value" terms
            if not isinstance(v, dict):
                raise ValueError(
                    f"[flat_object] field [{name}] must hold an object")
            for sub_path, leaf in _flat_leaves(v, ""):
                s = str(leaf)
                parsed.terms.setdefault(name, []).append(s)
                parsed.keywords.setdefault(name, []).append(s)
                parsed.terms.setdefault(f"{name}#paths", []).append(
                    f"{sub_path}={s}")
                parsed.keywords.setdefault(f"{name}#paths", []).append(
                    f"{sub_path}={s}")
            return
        if ft.type in RANGE_TYPES:
            lo, hi = _parse_range_value(ft, v)
            if lo > hi:
                raise ValueError(
                    f"[{ft.type}] field [{name}]: lower bound [{lo}] > "
                    f"upper bound [{hi}]")
            parsed.numerics.setdefault(f"{name}#lo", []).append(lo)
            parsed.numerics.setdefault(f"{name}#hi", []).append(hi)
            return
        if ft.type in ("keyword", "icu_collation_keyword"):
            s = str(v)
            if ft.ignore_above is not None and len(s) > ft.ignore_above:
                return
            norm = self.index_analyzer(ft).terms(s)
            s = norm[0] if norm else s
            if ft.index:
                parsed.terms.setdefault(name, []).append(s)
            if ft.doc_values:
                parsed.keywords.setdefault(name, []).append(s)
            return
        if ft.type in GEO_TYPES:
            lat, lon = _parse_geo(v)
            parsed.geos.setdefault(name, []).append((lat, lon))
            return
        if ft.type in SHAPE_TYPES:
            from ..search.geo import parse_shape
            # validate now (a bad shape is an index-time 400) and keep the
            # bbox so segment build doesn't re-parse every value
            sh = parse_shape(v)
            parsed.shapes.setdefault(name, []).append((v, sh.bbox))
            return
        if ft.type in FEATURE_TYPES:
            if not isinstance(v, dict):
                raise ValueError(
                    f"[{ft.type}] field [{name}] must hold an object of "
                    f"feature weights")
            bucket = parsed.features.setdefault(name, {})
            for feat, w in v.items():
                w = float(w)
                if w <= 0:
                    raise ValueError(
                        f"[{ft.type}] weights must be positive, got "
                        f"[{feat}]={w}")
                bucket[str(feat)] = w
            return
        if ft.type in VECTOR_TYPES:
            vec = [float(x) for x in (v if isinstance(v, list) else [v])]
            if ft.dims and len(vec) != ft.dims:
                raise ValueError(
                    f"vector length [{len(vec)}] differs from mapped dims "
                    f"[{ft.dims}] for field [{name}]")
            parsed.vectors[name] = vec
            return
        if ft.type == "completion":
            # suggester-only field: lives in _source, served by the host-side
            # prefix index (search/suggest.py completion_suggest)
            return
        cv = coerce_value(ft, v)
        parsed.numerics.setdefault(name, []).append(cv)
        if ft.type == "ip" and ft.index:
            parsed.terms.setdefault(name, []).append(str(v))


def _flat_leaves(obj: dict, prefix: str):
    """Depth-first (path, scalar) leaves of a flat_object value."""
    for k, v in obj.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            yield from _flat_leaves(v, f"{path}.")
        elif isinstance(v, list):
            for item in v:
                if isinstance(item, dict):
                    yield from _flat_leaves(item, f"{path}.")
                elif item is not None:
                    yield path, item
        elif v is not None:
            yield path, v


_RANGE_INT_BOUNDS = {
    "integer": (-(1 << 31), (1 << 31) - 1),
    "long": (-(1 << 63), (1 << 63) - 1),
    "date": (-(1 << 63), (1 << 63) - 1),
    "ip": (0, (1 << 63) - 1),
}


def _range_member_coerce(member: str, value: Any, ft: FieldType):
    if member == "date":
        return _parse_date(value, ft.date_format)
    if member == "ip":
        iv = _ip_to_int(str(value))
        if iv >= (1 << 63):
            raise ValueError(
                "ip_range supports IPv4(-mapped) addresses only in this "
                "engine (value exceeds the exact i64 column range)")
        return iv
    if member in ("integer", "long"):
        return int(value)
    return float(value)


def _parse_range_value(ft: FieldType, v: Any) -> Tuple[Any, Any]:
    """{gte/gt/lte/lt} -> closed [lo, hi] in column representation
    (reference RangeType: open bounds nudge by one ulp/step)."""
    import math

    if not isinstance(v, dict):
        raise ValueError(
            f"[{ft.type}] field [{ft.name}] must hold a range object")
    member = RANGE_MEMBER[ft.type]
    is_int = member in _RANGE_INT_BOUNDS
    lo_def, hi_def = (_RANGE_INT_BOUNDS[member] if is_int
                      else (-math.inf, math.inf))
    lo, hi = lo_def, hi_def
    for key, val in v.items():
        if val is None:
            continue
        cv = _range_member_coerce(member, val, ft)
        if key == "gte":
            lo = cv
        elif key == "gt":
            lo = cv + 1 if is_int else float(np_nextafter(cv, math.inf))
        elif key == "lte":
            hi = cv
        elif key == "lt":
            hi = cv - 1 if is_int else float(np_nextafter(cv, -math.inf))
        else:
            raise ValueError(f"unknown range bound [{key}]")
    return lo, hi


def np_nextafter(v, toward):
    import numpy as np
    return np.nextafter(np.float64(v), np.float64(toward))


def _parse_geo(v: Any) -> Tuple[float, float]:
    if isinstance(v, dict):
        return float(v["lat"]), float(v["lon"])
    if isinstance(v, str):
        lat, lon = v.split(",")
        return float(lat), float(lon)
    if isinstance(v, (list, tuple)):  # GeoJSON order [lon, lat]
        return float(v[1]), float(v[0])
    raise ValueError(f"cannot parse geo_point [{v}]")
