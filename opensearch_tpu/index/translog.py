"""Write-ahead log. Analog of reference
`index/translog/Translog.java`: every index/delete op is appended durably
before being acknowledged; on engine open, ops after the last commit point are
replayed. Format: JSONL generations (`translog-<gen>.log`)."""

from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

from ..obs import ingest_obs as _iobs


class Translog:
    def __init__(self, path: str, generation: int = 0):
        self.dir = path
        os.makedirs(path, exist_ok=True)
        self.generation = generation
        self._fh = open(self._gen_path(generation), "a", encoding="utf-8")
        self.ops_count = 0
        # generation start (monotonic): age of the oldest un-committed op
        # is bounded by now - this stamp, the `indexing.translog.age_s`
        # gauge the flush path publishes
        self._gen_started = time.monotonic()

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def add_index(self, doc_id: str, source: dict, routing: Optional[str], seq_no: int) -> None:
        self._append({"op": "index", "_id": doc_id, "_source": source,
                      "routing": routing, "seq_no": seq_no})

    def add_delete(self, doc_id: str, seq_no: int) -> None:
        self._append({"op": "delete", "_id": doc_id, "seq_no": seq_no})

    def _append(self, rec: dict) -> None:
        line = json.dumps(rec) + "\n"
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self.ops_count += 1
        if _iobs.enabled():
            _iobs.record_translog_append(len(line))

    def age_s(self) -> float:
        """Seconds since this generation started — an upper bound on the
        age of the oldest op not yet covered by a commit point."""
        return time.monotonic() - self._gen_started

    def rollover(self) -> int:
        """Start a new generation (at flush/commit); returns the new gen id
        (analog of Translog.rollGeneration)."""
        self._fh.close()
        self.generation += 1
        self._fh = open(self._gen_path(self.generation), "a", encoding="utf-8")
        self.ops_count = 0
        self._gen_started = time.monotonic()
        return self.generation

    def prune_below(self, gen: int) -> None:
        """Delete generations < gen, made durable by a commit point."""
        for g in range(gen):
            p = self._gen_path(g)
            if os.path.exists(p):
                os.remove(p)

    def replay_from(self, gen: int) -> Iterator[dict]:
        g = gen
        while True:
            p = self._gen_path(g)
            if not os.path.exists(p):
                break
            with open(p, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        yield json.loads(line)
            g += 1

    def close(self) -> None:
        self._fh.close()
