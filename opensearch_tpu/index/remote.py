"""Remote-backed storage: a blob-store mirror of every shard's committed
state, with incremental content-addressed uploads and restore-from-remote
recovery.

Reference: `index/store/RemoteSegmentStoreDirectory.java:1` (segment upload
/ download with checksum-tracked metadata), `RemoteSegmentTransferTracker.
java:1` (per-shard upload lag/bytes accounting), and the remote-store
restore flow of `RestoreRemoteStoreAction`. The TPU engine's segments are
immutable npz directories plus a JSON commit point, so the blob analog is
file-level: each flush uploads only files whose (size, md5) changed, writes
a generation manifest, then flips `latest.json` atomically — exactly the
two-phase commit the reference uses (segment files first, metadata last).

Layout under the remote root (any mounted/blob-like directory):
    <root>/<index>/meta.json                 index settings + mappings
    <root>/<index>/<shard>/files/<relpath>   segment + commit files
    <root>/<index>/<shard>/manifest-<n>.json file map {rel: {size, md5}}
    <root>/<index>/<shard>/latest.json       {"gen": n}
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional


def _md5(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.md5()
    with open(path, "rb") as fh:
        while True:
            b = fh.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _atomic_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


class TransferTracker:
    """Per-shard upload accounting (reference RemoteSegmentTransferTracker):
    bytes moved vs skipped (dedup hits), wall time, and commit lag."""

    def __init__(self):
        self.uploads = 0
        self.bytes_uploaded = 0
        self.files_uploaded = 0
        self.files_skipped = 0
        self.last_upload_ms = 0.0
        self.last_upload_ts = 0.0
        self.failures = 0
        self.local_gen = 0
        self.remote_gen = 0

    @property
    def lag(self) -> int:
        """Commits the remote is behind the local shard."""
        return max(0, self.local_gen - self.remote_gen)

    def stats(self) -> dict:
        return {"uploads": self.uploads,
                "bytes_uploaded": self.bytes_uploaded,
                "files_uploaded": self.files_uploaded,
                "files_skipped_dedup": self.files_skipped,
                "last_upload_ms": round(self.last_upload_ms, 2),
                "failures": self.failures,
                "local_gen": self.local_gen,
                "remote_gen": self.remote_gen,
                "refresh_lag": self.lag}


class RemoteSegmentStore:
    """One index's remote mirror."""

    def __init__(self, root: str, index: str):
        self.root = root
        self.index = index
        self.base = os.path.join(root, index)
        self.trackers: Dict[int, TransferTracker] = {}
        # meta.json upload failures: without this, a mirror missing its
        # index metadata (restore can't find the index) would look healthy
        self.meta_failures = 0

    # ---------------- upload ----------------

    def upload_index_meta(self, meta: dict) -> None:
        try:
            os.makedirs(self.base, exist_ok=True)
            _atomic_json(os.path.join(self.base, "meta.json"), meta)
        except Exception:
            # counted HERE so every call site keeps the invariant: a mirror
            # whose meta.json is missing/stale must never look healthy
            self.meta_failures += 1
            raise

    def tracker(self, shard_id: int) -> TransferTracker:
        t = self.trackers.get(shard_id)
        if t is None:
            t = self.trackers[shard_id] = TransferTracker()
        return t

    def upload_shard(self, local_path: str, shard_id: int) -> dict:
        """Mirror one shard's committed files (segments/ + commit.json).
        Incremental: files whose (size, md5) already match the previous
        manifest are skipped — segment immutability makes this the common
        case, so repeat flushes move only new segments and the commit
        point. The manifest write is last: a crashed upload leaves the
        previous generation fully restorable."""
        t = self.tracker(shard_id)
        t.local_gen += 1
        t0 = time.monotonic()
        sdir = os.path.join(self.base, str(shard_id))
        fdir = os.path.join(sdir, "files")
        files: Dict[str, dict] = {}
        try:
            os.makedirs(fdir, exist_ok=True)
            prev = {}
            gen = 0
            latest = os.path.join(sdir, "latest.json")
            if os.path.exists(latest):
                with open(latest) as fh:
                    gen = json.load(fh)["gen"]
                mpath = os.path.join(sdir, f"manifest-{gen}.json")
                if os.path.exists(mpath):
                    with open(mpath) as fh:
                        prev = json.load(fh)["files"]
            new_gen = gen + 1
            for rel in self._committed_files(local_path):
                src = os.path.join(local_path, rel)
                st = os.stat(src)
                size = st.st_size
                old = prev.get(rel)
                if old and old["size"] == size \
                        and old.get("mtime") == st.st_mtime_ns:
                    # unchanged by (size, mtime): skip both the hash and the
                    # copy — a no-op flush must not re-stream the shard
                    files[rel] = old
                    t.files_skipped += 1
                    continue
                digest = _md5(src)
                if old and old["size"] == size and old["md5"] == digest:
                    files[rel] = dict(old, mtime=st.st_mtime_ns)
                    t.files_skipped += 1   # touched but identical content
                    continue
                # changed content goes to a NEW generation-suffixed blob —
                # never overwrite a path the previous manifest references,
                # or a crash mid-upload would corrupt the restorable
                # generation (commit.json changes every flush)
                stored = f"{rel}.g{new_gen}" if old else rel
                files[rel] = {"size": size, "md5": digest,
                              "mtime": st.st_mtime_ns, "path": stored}
                dst = os.path.join(fdir, stored)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                shutil.copy2(src, dst)
                t.files_uploaded += 1
                t.bytes_uploaded += size
            _atomic_json(os.path.join(sdir, f"manifest-{new_gen}.json"),
                         {"files": files, "ts": time.time()})
            _atomic_json(latest, {"gen": new_gen})
            # prune ONLY after the new generation is live: a crash anywhere
            # above leaves the previous manifest's blobs intact, so the
            # prior generation stays fully restorable (two-phase commit)
            live_paths = {f.get("path", rel) for rel, f in files.items()}
            for rel, f in prev.items():
                stored = f.get("path", rel)
                if stored in live_paths:
                    continue
                stale = os.path.join(fdir, stored)
                if os.path.exists(stale):
                    os.remove(stale)
                # drop now-empty segment dirs so the mirror mirrors
                d = os.path.dirname(stale)
                while d != fdir and os.path.isdir(d) and not os.listdir(d):
                    os.rmdir(d)
                    d = os.path.dirname(d)
            old_manifest = os.path.join(sdir, f"manifest-{gen}.json")
            if gen and os.path.exists(old_manifest):
                os.remove(old_manifest)
        except Exception:
            # not just OSError: a corrupt latest.json/manifest (partial
            # transfer, other writer) raises JSONDecodeError/KeyError —
            # every failure mode must count before propagating
            t.failures += 1
            raise
        t.remote_gen = t.local_gen
        t.uploads += 1
        t.last_upload_ms = (time.monotonic() - t0) * 1000.0
        t.last_upload_ts = time.time()
        return {"gen": t.remote_gen, "files": len(files)}

    @staticmethod
    def _committed_files(local_path: str) -> List[str]:
        """Files belonging to the CURRENT commit point only — the local
        segments dir may still hold merged-away segments the commit no
        longer references; mirroring those would grow the remote
        unboundedly."""
        out = []
        commit = os.path.join(local_path, "commit.json")
        if not os.path.exists(commit):
            return out
        out.append("commit.json")
        with open(commit) as fh:
            committed = set(json.load(fh).get("segments", []))
        seg_root = os.path.join(local_path, "segments")
        if os.path.isdir(seg_root):
            for seg_name in sorted(committed):
                d = os.path.join(seg_root, seg_name)
                for dirpath, _dirs, names in os.walk(d):
                    for n in names:
                        full = os.path.join(dirpath, n)
                        out.append(os.path.relpath(full, local_path))
        return out

    # ---------------- restore ----------------

    def restore_shard(self, shard_id: int, dest_path: str) -> int:
        """Materialize the latest remote generation into a local shard dir.
        Returns the number of files restored."""
        sdir = os.path.join(self.base, str(shard_id))
        latest = os.path.join(sdir, "latest.json")
        if not os.path.exists(latest):
            return 0
        with open(latest) as fh:
            gen = json.load(fh)["gen"]
        with open(os.path.join(sdir, f"manifest-{gen}.json")) as fh:
            files = json.load(fh)["files"]
        n = 0
        for rel, meta in files.items():
            src = os.path.join(sdir, "files", meta.get("path", rel))
            dst = os.path.join(dest_path, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy2(src, dst)
            n += 1
        t = self.tracker(shard_id)
        t.remote_gen = t.local_gen = gen
        return n

    def load_index_meta(self) -> Optional[dict]:
        p = os.path.join(self.base, "meta.json")
        if not os.path.exists(p):
            return None
        with open(p) as fh:
            return json.load(fh)

    def shard_ids(self) -> List[int]:
        if not os.path.isdir(self.base):
            return []
        return sorted(int(d) for d in os.listdir(self.base) if d.isdigit())

    def stats(self) -> dict:
        return {"shards": {str(sid): t.stats()
                           for sid, t in sorted(self.trackers.items())},
                "meta_failures": self.meta_failures}


def remote_indices(root: str) -> List[str]:
    """Index names present under a remote root."""
    if not root or not os.path.isdir(root):
        return []
    return sorted(n for n in os.listdir(root)
                  if os.path.exists(os.path.join(root, n, "meta.json")))
