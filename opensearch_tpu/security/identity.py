"""Identity & access control — the analog of the reference's identity
subsystem (`server/src/main/java/org/opensearch/identity/IdentityService.java:1`,
`identity/tokens/BasicAuthToken.java:1`, `identity/tokens/BearerAuthToken.java:1`)
plus the index/action permission model of the security plugin the reference
ecosystem layers on top (`plugins/identity-shiro/.../ShiroIdentityPlugin.java:1`
is the in-tree example).

Scope vs the reference: the full security plugin carries TLS, LDAP/SAML/
OIDC backends, DLS/FLS and audit logging; this build implements the core
the API contract needs — an internal user store (PBKDF2-hashed passwords),
roles with cluster/index permission patterns, HTTP Basic + bearer-token
authentication, and per-request authorization — so a cluster can actually
refuse unauthenticated writes. Disabled by default (like a reference
distribution without the plugin): enabling is one `IdentityService` with
users attached to the `HttpServer`/`Node`.

Design: everything is plain host-side Python — auth gates the transport
layer; nothing here touches the device path.
"""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import hmac
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set


class AuthenticationError(Exception):
    """401: missing/invalid credentials."""


class AuthorizationError(Exception):
    """403: authenticated but not permitted."""


# action groups (the reference security plugin's action-group granularity,
# collapsed to the buckets this engine's REST surface distinguishes)
READ = "read"          # search/get/aggregation/termvectors
WRITE = "write"        # doc CRUD, bulk, update_by_query
INDEX_ADMIN = "manage" # create/delete/settings/mappings/open/close
CLUSTER_ADMIN = "cluster_admin"  # cluster settings, snapshots, templates
ALL = "all"

_ACTIONS = {READ, WRITE, INDEX_ADMIN, CLUSTER_ADMIN, ALL}


def _hash_password(password: str, salt: bytes, rounds: int = 60_000) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                               rounds)


@dataclass
class Role:
    """Named permission set: index patterns -> allowed action groups,
    plus cluster-level actions (reference roles.yml shape)."""
    name: str
    cluster: Set[str] = field(default_factory=set)
    # list of (glob pattern, {actions})
    indices: List = field(default_factory=list)

    @classmethod
    def parse(cls, name: str, body: dict) -> "Role":
        cluster = set(body.get("cluster_permissions", []))
        bad = cluster - _ACTIONS
        if bad:
            raise ValueError(f"unknown cluster permissions {sorted(bad)}")
        indices = []
        for ip in body.get("index_permissions", []):
            pats = ip.get("index_patterns", ["*"])
            acts = set(ip.get("allowed_actions", []))
            bad = acts - _ACTIONS
            if bad:
                raise ValueError(f"unknown index actions {sorted(bad)}")
            for p in (pats if isinstance(pats, list) else [pats]):
                indices.append((p, acts))
        return cls(name=name, cluster=cluster, indices=indices)

    def allows_cluster(self, action: str) -> bool:
        return ALL in self.cluster or action in self.cluster

    def allows_index(self, index: str, action: str) -> bool:
        for pat, acts in self.indices:
            if _glob_match(pat, index) and (ALL in acts or action in acts):
                return True
        return False


def _glob_match(pattern: str, name: str) -> bool:
    return fnmatch.fnmatchcase(name, pattern)


@dataclass
class User:
    name: str
    salt: bytes
    pw_hash: bytes
    roles: List[str] = field(default_factory=list)
    attributes: dict = field(default_factory=dict)

    def check_password(self, password: str) -> bool:
        return hmac.compare_digest(self.pw_hash,
                                   _hash_password(password, self.salt))


@dataclass
class Subject:
    """An authenticated principal (reference identity/Subject.java:1)."""
    principal: str
    roles: List[str]

    def __str__(self) -> str:  # NamedPrincipal.getName()
        return self.principal


class IdentityService:
    """User store + token manager + authorizer.

    Reference: `identity/IdentityService.java:1` (plugin discovery, subject
    lookup), `identity/tokens/TokenManager.java:1` (token issue/reset).
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        # handler threads mutate users/roles/_tokens concurrently
        self._lock = threading.RLock()
        self.users: Dict[str, User] = {}
        self.roles: Dict[str, Role] = {
            # built-ins mirroring the reference defaults
            "all_access": Role("all_access", cluster={ALL},
                               indices=[("*", {ALL})]),
            "readall": Role("readall", cluster=set(),
                            indices=[("*", {READ})]),
        }
        # bearer tokens: token -> (principal, expiry_epoch)
        self._tokens: Dict[str, tuple] = {}

    # ---------------- user / role CRUD ----------------

    def put_user(self, name: str, password: str,
                 roles: Optional[List[str]] = None,
                 attributes: Optional[dict] = None) -> None:
        if not password or len(password) < 6:
            raise ValueError("password must be at least 6 characters")
        salt = os.urandom(16)
        with self._lock:
            self.users[name] = User(name=name, salt=salt,
                                    pw_hash=_hash_password(password, salt),
                                    roles=list(roles or []),
                                    attributes=dict(attributes or {}))

    def delete_user(self, name: str) -> bool:
        with self._lock:
            self._tokens = {t: v for t, v in self._tokens.items()
                            if v[0] != name}
            return self.users.pop(name, None) is not None

    def put_role(self, name: str, body: dict) -> None:
        role = Role.parse(name, body)
        with self._lock:
            self.roles[name] = role

    def delete_role(self, name: str) -> bool:
        with self._lock:
            return self.roles.pop(name, None) is not None

    # ---------------- authentication ----------------

    def authenticate_basic(self, username: str, password: str) -> Subject:
        u = self.users.get(username)
        # constant-shape check: hash even for unknown users so the
        # timing side channel can't enumerate principals
        if u is None:
            _hash_password(password, b"\x00" * 16)
            raise AuthenticationError("invalid credentials")
        if not u.check_password(password):
            raise AuthenticationError("invalid credentials")
        return Subject(principal=u.name, roles=list(u.roles))

    def issue_token(self, subject: Subject,
                    ttl_seconds: float = 3600.0) -> str:
        """Reference TokenManager.issueOnBehalfOfToken (opaque bearer)."""
        tok = secrets.token_urlsafe(24)
        with self._lock:
            self._tokens[tok] = (subject.principal,
                                 time.time() + ttl_seconds)
        return tok

    def authenticate_bearer(self, token: str) -> Subject:
        with self._lock:
            ent = self._tokens.get(token)
            if ent is not None and time.time() > ent[1]:
                self._tokens.pop(token, None)
                raise AuthenticationError("token expired")
        if ent is None:
            raise AuthenticationError("invalid token")
        principal, _exp = ent
        u = self.users.get(principal)
        if u is None:
            raise AuthenticationError("token principal no longer exists")
        return Subject(principal=u.name, roles=list(u.roles))

    def authenticate_header(self, authorization: Optional[str]) -> Subject:
        """Parse an HTTP Authorization header (reference
        `identity/tokens/RestTokenExtractor.java:1`)."""
        if not authorization:
            raise AuthenticationError("missing authentication credentials")
        scheme, _, rest = authorization.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic":
            try:
                up = base64.b64decode(rest.strip()).decode("utf-8")
                username, _, password = up.partition(":")
            except Exception:
                raise AuthenticationError("malformed basic credentials")
            return self.authenticate_basic(username, password)
        if scheme == "bearer":
            return self.authenticate_bearer(rest.strip())
        raise AuthenticationError(f"unsupported auth scheme [{scheme}]")

    # ---------------- authorization ----------------

    def _roles_of(self, subject: Subject) -> List[Role]:
        return [self.roles[r] for r in subject.roles if r in self.roles]

    def authorize_cluster(self, subject: Subject, action: str) -> None:
        if any(r.allows_cluster(action) for r in self._roles_of(subject)):
            return
        raise AuthorizationError(
            f"no permissions for cluster action [{action}] and user "
            f"[{subject.principal}]")

    def authorize_index(self, subject: Subject, index: str,
                        action: str) -> None:
        if any(r.allows_index(index, action)
               for r in self._roles_of(subject)):
            return
        raise AuthorizationError(
            f"no permissions for [{action}] on index [{index}] and user "
            f"[{subject.principal}]")
