"""Per-request security context — the compact analog of the reference's
`ThreadContext` (`common/util/concurrent/ThreadContext.java:1`), which
carries the authenticated subject through every layer of a request so
authorization can re-check targets that only become known mid-flight
(alias resolution, ingest-pipeline `_index` rewrites).

The HTTP handler installs (identity, subject) for the request's duration;
`RestClient` consults it at points where the effective target index can
DIFFER from the one the transport already authorized."""

from __future__ import annotations

import threading
from contextlib import contextmanager

_CTX = threading.local()


@contextmanager
def request_subject(identity, subject):
    prev = getattr(_CTX, "entry", None)
    _CTX.entry = (identity, subject)
    try:
        yield
    finally:
        _CTX.entry = prev


def authorize_index_if_active(index: str, action: str) -> None:
    """Re-check an index target against the ambient request subject.
    No-op when no security context is active (open cluster / library
    use); raises AuthorizationError like the transport-level check."""
    entry = getattr(_CTX, "entry", None)
    if entry is None:
        return
    identity, subject = entry
    identity.authorize_index(subject, index, action)
