from .identity import (AuthenticationError, AuthorizationError,
                       IdentityService, Role, Subject, User)

__all__ = ["IdentityService", "User", "Role", "Subject",
           "AuthenticationError", "AuthorizationError"]
