"""Cluster state & index metadata. Analog of reference
`cluster/ClusterState.java` + `cluster/metadata/IndexMetadata.java` /
`MetadataCreateIndexService`. Single-controller model: one Node owns the
authoritative state (the JAX-style single-Python-process control plane; the
multi-host story distributes *data*, not control — see parallel/)."""

from __future__ import annotations

import fnmatch
import time
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional


@dataclass
class IndexMetadata:
    name: str
    settings: dict = dc_field(default_factory=dict)
    creation_date: float = dc_field(default_factory=time.time)
    state: str = "open"

    @property
    def num_shards(self) -> int:
        s = self.settings.get("index", {}).get("number_of_shards",
                                               self.settings.get("number_of_shards", 1))
        return int(s)

    @property
    def num_replicas(self) -> int:
        s = self.settings.get("index", {}).get("number_of_replicas",
                                               self.settings.get("number_of_replicas", 1))
        return int(s)


@dataclass
class AliasMetadata:
    alias: str
    indices: Dict[str, dict] = dc_field(default_factory=dict)  # index -> {filter, is_write_index}


class ClusterStateError(Exception):
    pass


class IndexNotFoundError(ClusterStateError):
    """HTTP 404 analog."""


class ResourceAlreadyExistsError(ClusterStateError):
    """HTTP 400 analog of ResourceAlreadyExistsException."""


class ClusterMetadata:
    """Indices, aliases, templates, stored ingest pipeline configs."""

    def __init__(self, cluster_name: str = "opensearch-tpu"):
        self.cluster_name = cluster_name
        self.indices: Dict[str, IndexMetadata] = {}
        self.aliases: Dict[str, AliasMetadata] = {}
        self.templates: Dict[str, dict] = {}
        # data streams (cluster/datastream.py; reference DataStream.java)
        self.data_streams: Dict[str, "object"] = {}
        self.version = 0

    def bump(self) -> None:
        self.version += 1

    # ---------------- index name resolution ----------------

    def resolve(self, expression, allow_no_indices: bool = True) -> List[str]:
        """Wildcards, comma lists, aliases -> concrete index names (reference
        IndexNameExpressionResolver)."""
        if expression in (None, "", "_all", "*"):
            return sorted(self.indices)
        exprs = expression if isinstance(expression, list) else str(expression).split(",")
        out: List[str] = []
        for ex in exprs:
            ex = ex.strip()
            if ex in self.indices:
                out.append(ex)
                continue
            if ex in self.data_streams:
                out.extend(self.data_streams[ex].indices)
                continue
            if ex in self.aliases:
                out.extend(sorted(self.aliases[ex].indices))
                continue
            if "*" in ex or "?" in ex:
                matched = [n for n in self.indices if fnmatch.fnmatch(n, ex)]
                matched += [n for a, am in self.aliases.items()
                            if fnmatch.fnmatch(a, ex) for n in am.indices]
                matched += [n for d, ds in self.data_streams.items()
                            if fnmatch.fnmatch(d, ex) for n in ds.indices]
                out.extend(sorted(set(matched)))
                continue
            raise IndexNotFoundError(f"no such index [{ex}]")
        seen = set()
        uniq = [x for x in out if not (x in seen or seen.add(x))]
        if not uniq and not allow_no_indices:
            raise IndexNotFoundError(f"no indices match [{expression}]")
        return uniq

    def write_index(self, name: str) -> str:
        """Resolve an alias or data stream to its write index."""
        if name in self.indices:
            return name
        ds = self.data_streams.get(name)
        if ds is not None:
            return ds.write_index
        am = self.aliases.get(name)
        if am is not None:
            writes = [i for i, cfg in am.indices.items() if cfg.get("is_write_index")]
            if len(writes) == 1:
                return writes[0]
            if len(am.indices) == 1:
                return next(iter(am.indices))
            raise ClusterStateError(
                f"alias [{name}] has multiple indices and no write index")
        raise IndexNotFoundError(f"no such index [{name}]")

    def matching_templates(self, index_name: str) -> List[dict]:
        matches = [t for t in self.templates.values()
                   if any(fnmatch.fnmatch(index_name, p)
                          for p in t.get("index_patterns", []))]
        return sorted(matches, key=lambda t: -t.get("priority", t.get("order", 0)))
