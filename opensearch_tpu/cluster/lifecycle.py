"""Index lifecycle management (ILM/ISM-lite).

Reference: the ISM plugin's policy states + the core `_rollover` API
(`action/admin/indices/rollover/`). Policies are simplified to the two
actions that matter operationally — rollover (max_docs / max_age on the
write index behind an alias) and delete (min_age) — and the state machine
ticks DETERMINISTICALLY via `step()` instead of a background scheduler (the
caller owns the clock; a cron wrapper recovers the reference behavior)."""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional


def parse_age_s(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suf, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0),
                      ("d", 86400.0)):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def next_rollover_name(index: str) -> str:
    m = re.fullmatch(r"(.*)-(\d{6})", index)
    if m:
        return f"{m.group(1)}-{int(m.group(2)) + 1:06d}"
    return f"{index}-000002"


class LifecycleService:
    def __init__(self, node):
        self.node = node
        self.policies: Dict[str, dict] = {}
        self.history: List[dict] = []

    def put_policy(self, name: str, body: dict) -> None:
        """Validate up front: a bad policy must be a 400 at PUT time, not a
        crash inside every subsequent step() tick."""
        policy = body.get("policy", body)
        ro = policy.get("rollover") or {}
        unknown = set(ro) - {"max_docs", "max_age"}
        if unknown:
            raise ValueError(
                f"unknown rollover condition{'s' if len(unknown) > 1 else ''} "
                f"{sorted(unknown)}")
        dl = policy.get("delete") or {}
        unknown = set(dl) - {"min_age"}
        if unknown:
            raise ValueError(f"unknown delete setting {sorted(unknown)}")
        fm = policy.get("force_merge") or {}
        unknown = set(fm) - {"min_age", "max_num_segments"}
        if unknown:
            raise ValueError(f"unknown force_merge setting {sorted(unknown)}")
        ro_only = policy.get("read_only") or {}
        unknown = set(ro_only) - {"min_age"}
        if unknown:
            raise ValueError(f"unknown read_only setting {sorted(unknown)}")
        unknown = set(policy) - {"rollover", "delete", "force_merge",
                                 "read_only"}
        if unknown:
            raise ValueError(f"unknown lifecycle action{'s' if len(unknown) > 1 else ''} "
                             f"{sorted(unknown)}")
        # values must parse too — a bad duration is a 400 here, not a crash
        # inside every subsequent tick
        for label, v in (("rollover.max_age", ro.get("max_age")),
                         ("delete.min_age", dl.get("min_age")),
                         ("force_merge.min_age", fm.get("min_age")),
                         ("read_only.min_age", ro_only.get("min_age"))):
            if v is not None:
                try:
                    parse_age_s(v)
                except ValueError:
                    raise ValueError(f"cannot parse duration [{v}] "
                                     f"for [{label}]")
        for label, v in (("rollover.max_docs", ro.get("max_docs")),
                         ("force_merge.max_num_segments",
                          fm.get("max_num_segments"))):
            if v is not None:
                try:
                    int(v)
                except (TypeError, ValueError):
                    raise ValueError(f"cannot parse [{label}] value [{v}]")
        self.policies[name] = policy

    def get_policy(self, name: str) -> Optional[dict]:
        return self.policies.get(name)

    def _policy_for(self, meta) -> Optional[dict]:
        idx = meta.settings.get("index", meta.settings)
        lc = idx.get("lifecycle", {})
        pname = lc.get("name") if isinstance(lc, dict) else None
        pname = pname or idx.get("lifecycle.name")
        return self.policies.get(pname) if pname else None

    def _rollover_alias(self, meta) -> Optional[str]:
        idx = meta.settings.get("index", meta.settings)
        lc = idx.get("lifecycle", {})
        alias = lc.get("rollover_alias") if isinstance(lc, dict) else None
        return alias or idx.get("lifecycle.rollover_alias")

    def explain(self, index: str) -> dict:
        meta = self.node.metadata.indices[index]
        policy = self._policy_for(meta)
        return {"index": index, "managed": policy is not None,
                "policy": policy,
                "age_seconds": time.time() - meta.creation_date}  # oslint: disable=OSL501 -- age vs PERSISTED wall-clock creation epoch; monotonic cannot span restarts

    def check_conditions(self, index: str, conds: dict,
                         now: Optional[float] = None) -> dict:
        """Evaluate rollover conditions for one index (reference
        RolloverRequest conditions; unknown keys are a client error)."""
        now = now if now is not None else time.time()
        meta = self.node.metadata.indices[index]
        results = {}
        for key, v in conds.items():
            if key == "max_docs":
                results["[max_docs]"] = (
                    self.node.indices[index].num_docs >= int(v))
            elif key == "max_age":
                results["[max_age]"] = (
                    now - meta.creation_date >= parse_age_s(v))
            else:
                raise ValueError(f"unknown rollover condition [{key}]")
        return results

    def _is_write_index(self, name: str, alias: Optional[str]) -> bool:
        if not alias:
            return False
        try:
            return self.node.metadata.write_index(alias) == name
        except Exception:
            return False

    def step(self, now: Optional[float] = None) -> List[dict]:
        """One deterministic lifecycle tick over every managed index.
        Rollover is considered first; the CURRENT write index of a rollover
        series is never deleted (it must roll out of write duty first, like
        the reference ISM state machine). Returns the actions taken."""
        now = now if now is not None else time.time()
        actions = []
        for name in list(self.node.indices.keys()):
            meta = self.node.metadata.indices.get(name)
            if meta is None:
                continue
            policy = self._policy_for(meta)
            if not policy:
                continue
            age = now - meta.creation_date
            ro = policy.get("rollover")
            alias = self._rollover_alias(meta)
            is_write = self._is_write_index(name, alias)
            if ro and alias and is_write:
                try:
                    results = self.check_conditions(name, ro, now)
                except ValueError as e:
                    # a policy edited behind put_policy's back must not brick
                    # the whole tick — record and move on
                    actions.append({"index": name, "action": "error",
                                    "reason": str(e)})
                    continue
                if results and any(results.values()):
                    docs = self.node.indices[name].num_docs
                    new_name = self._do_rollover(alias, name)
                    actions.append({"index": name, "action": "rollover",
                                    "new_index": new_name,
                                    "docs": docs, "age_seconds": age})
                    continue
            idx_settings = meta.settings.setdefault("index", {})
            lc_state = idx_settings.setdefault("lifecycle", {})
            try:
                fm = policy.get("force_merge")
                if (fm and not (ro and is_write)
                        and not lc_state.get("force_merged")
                        and age >= parse_age_s(fm.get("min_age", "0ms"))):
                    # the service helper also re-syncs replicas: merged
                    # segments replace shared objects, and a replica left
                    # on the old set would serve pre-merge deletes
                    self.node.indices[name].force_merge(
                        int(fm.get("max_num_segments", 1)))
                    lc_state["force_merged"] = True
                    actions.append({"index": name, "action": "force_merge",
                                    "age_seconds": age})
                ronly = policy.get("read_only")
                if (ronly and not (ro and is_write)
                        and not idx_settings.get("blocks", {}).get("write")
                        and age >= parse_age_s(ronly.get("min_age", "0ms"))):
                    idx_settings.setdefault("blocks", {})["write"] = True
                    actions.append({"index": name, "action": "read_only",
                                    "age_seconds": age})
            except (TypeError, ValueError) as e:
                actions.append({"index": name, "action": "error",
                                "reason": str(e)})
                continue
            delete_cfg = policy.get("delete")
            if delete_cfg and not (ro and is_write):
                try:
                    min_age = parse_age_s(delete_cfg.get("min_age", "0ms"))
                except ValueError as e:
                    actions.append({"index": name, "action": "error",
                                    "reason": str(e)})
                    continue
                if age >= min_age:
                    from .datastream import DataStreamError
                    try:
                        # guard-exempt: ILM may reap rolled-over backing
                        # indices (never a stream's write index)
                        self.node.delete_index(name, _ds_guard=False)
                        actions.append({"index": name, "action": "delete",
                                        "age_seconds": age})
                    except DataStreamError as e:
                        actions.append({"index": name, "action": "error",
                                        "reason": str(e)})
        self.history.extend(actions)
        return actions

    def rollover(self, alias: str, old_index: str) -> str:
        """Roll the series: create the next index and move the write alias
        (shared by the _rollover API and step())."""
        new_name = self._do_rollover(alias, old_index)
        self.history.append({"index": old_index, "action": "rollover",
                             "new_index": new_name})
        return new_name

    def _do_rollover(self, alias: str, old_index: str) -> str:
        import copy
        node = self.node
        new_name = next_rollover_name(old_index)
        old_meta = node.metadata.indices[old_index]
        # deep copy: create_index installs the inner "index" dict by
        # reference, and the series must not share mutable settings.
        # Transient lifecycle STATE must not travel to the new index — a
        # rolled-to index must not be born read-only or force_merged
        settings = copy.deepcopy(old_meta.settings)
        idx = settings.get("index", {})
        idx.pop("blocks", None)
        if isinstance(idx.get("lifecycle"), dict):
            idx["lifecycle"].pop("force_merged", None)
        node.create_index(new_name, {"settings": settings,
                                     "mappings":
                                         node.indices[old_index].mappings.to_dict()})
        am = node.metadata.aliases.get(alias)
        if am is not None:
            for idx in am.indices:
                am.indices[idx] = dict(am.indices[idx],
                                       is_write_index=False)
            am.indices[new_name] = {"is_write_index": True}
        node.metadata.bump()
        return new_name
