from .node import IndexService, Node
from .routing import shard_for
from .state import ClusterMetadata, IndexMetadata, IndexNotFoundError

__all__ = ["Node", "IndexService", "shard_for", "ClusterMetadata",
           "IndexMetadata", "IndexNotFoundError"]
