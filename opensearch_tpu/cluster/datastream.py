"""Data streams: append-only time-series abstractions over generations of
backing indices.

Reference analogs: `cluster/metadata/DataStream.java` (generation counter,
backing-index naming, timestamp field), `action/admin/indices/datastream/
{Create,Get,Delete}DataStreamAction.java`, and the rollover path in
`action/admin/indices/rollover/` (a data-stream rollover creates the next
backing generation and moves the write target).

TPU-design note: a data stream is pure host-side metadata — each backing
index is an ordinary index whose segments live in HBM; searches expand the
stream to its backing indices and ride the normal shard fan-out, so a
stream behaves like any multi-index expression to the device path.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional

from .state import ClusterStateError, IndexNotFoundError

TIMESTAMP_FIELD = "@timestamp"


@dataclass
class DataStreamMetadata:
    name: str
    generation: int = 1
    indices: List[str] = dc_field(default_factory=list)

    @property
    def write_index(self) -> str:
        return self.indices[-1]

    def to_dict(self) -> dict:
        return {"name": self.name,
                "timestamp_field": {"name": TIMESTAMP_FIELD},
                "generation": self.generation,
                "indices": [{"index_name": n} for n in self.indices],
                "status": "GREEN", "template": ""}


class DataStreamError(ClusterStateError):
    """HTTP 400 analog for data-stream rule violations."""


def backing_name(stream: str, generation: int) -> str:
    return f".ds-{stream}-{generation:06d}"


def _matching_ds_template(node, name: str) -> Optional[dict]:
    for tmpl in node.metadata.matching_templates(name):
        if "data_stream" in tmpl:
            return tmpl
    return None


def create_data_stream(node, name: str) -> dict:
    if name in node.metadata.data_streams:
        raise DataStreamError(f"data_stream [{name}] already exists")
    if name in node.indices or name in node.metadata.aliases:
        raise DataStreamError(
            f"[{name}] already exists as an index or alias")
    tmpl = _matching_ds_template(node, name)
    if tmpl is None:
        raise DataStreamError(
            f"no matching index template with a data_stream definition "
            f"for [{name}]")
    backing = backing_name(name, 1)
    _create_backing(node, name, backing)
    ds = DataStreamMetadata(name=name, generation=1, indices=[backing])
    node.metadata.data_streams[name] = ds
    node.metadata.bump()
    node._persist_data_streams()
    return {"acknowledged": True}


def _create_backing(node, stream: str, backing: str) -> None:
    """Create one backing index with the STREAM-matched template applied
    (templates match the stream name, not the .ds-* backing name). A bad
    template (e.g. non-date @timestamp) rolls the index creation back so
    no orphaned backing index survives."""
    tmpl = _matching_ds_template(node, stream) or {}
    tbody = tmpl.get("template", {})
    node.create_index(backing, {"settings": tbody.get("settings", {}),
                                "mappings": tbody.get("mappings")})
    try:
        _ensure_timestamp_mapping(node, backing)
    except DataStreamError:
        node.delete_index(backing, _ds_guard=False)
        raise


def _ensure_timestamp_mapping(node, index: str) -> None:
    svc = node.indices[index]
    ft = svc.mappings.resolve_field(TIMESTAMP_FIELD)
    if ft is None:
        svc.mappings.merge({"properties": {TIMESTAMP_FIELD: {"type": "date"}}})
    elif ft.type != "date":
        raise DataStreamError(
            f"data stream timestamp field [{TIMESTAMP_FIELD}] must be a "
            f"date, found [{ft.type}]")


def get_data_streams(node, expression: str = "*") -> List[dict]:
    import fnmatch
    out = []
    for name in sorted(node.metadata.data_streams):
        if expression in ("*", "_all", "", None) \
                or fnmatch.fnmatch(name, expression) \
                or name == expression:
            out.append(node.metadata.data_streams[name].to_dict())
    if not out and expression not in ("*", "_all", "", None) \
            and "*" not in str(expression):
        raise IndexNotFoundError(f"no such data stream [{expression}]")
    return out


def delete_data_stream(node, expression: str) -> dict:
    import fnmatch
    names = [n for n in list(node.metadata.data_streams)
             if n == expression or fnmatch.fnmatch(n, str(expression))]
    if not names:
        raise IndexNotFoundError(f"no such data stream [{expression}]")
    for name in names:
        ds = node.metadata.data_streams.pop(name)
        for idx in ds.indices:
            if idx in node.indices:
                node.delete_index(idx)
    node.metadata.bump()
    node._persist_data_streams()
    return {"acknowledged": True}


def rollover_data_stream(node, name: str) -> dict:
    ds = node.metadata.data_streams.get(name)
    if ds is None:
        raise IndexNotFoundError(f"no such data stream [{name}]")
    old = ds.write_index
    new = backing_name(name, ds.generation + 1)
    _create_backing(node, name, new)    # state mutates only on success
    ds.generation += 1
    ds.indices.append(new)
    node.metadata.bump()
    node._persist_data_streams()
    return {"acknowledged": True, "old_index": old, "new_index": new,
            "rolled_over": True, "dry_run": False}


def check_write(node, target: str, op_type: str, body: Optional[dict]) -> None:
    """Data-stream write rules (reference DataStream.validate): only
    op_type=create appends, and every document carries @timestamp."""
    if target not in node.metadata.data_streams:
        return
    if op_type != "create":
        raise DataStreamError(
            f"only write ops with an op_type of create are allowed in "
            f"data streams [{target}]")
    if not isinstance(body, dict) or TIMESTAMP_FIELD not in body:
        raise DataStreamError(
            f"documents must contain a [{TIMESTAMP_FIELD}] field in data "
            f"stream [{target}]")


def guard_backing_delete(node, index: str) -> None:
    for ds in node.metadata.data_streams.values():
        if index in ds.indices:
            raise DataStreamError(
                f"index [{index}] is a backing index of data stream "
                f"[{ds.name}]; delete the data stream instead")


def is_backing(node, index: str) -> Optional[str]:
    for ds in node.metadata.data_streams.values():
        if index in ds.indices:
            return ds.name
    return None


def release_deleted(node, deleted: List[str]) -> None:
    """Keep stream metadata consistent after backing indices were removed
    through a guard-exempt path (ILM delete action)."""
    changed = False
    for ds in node.metadata.data_streams.values():
        kept = [i for i in ds.indices if i not in deleted]
        if len(kept) != len(ds.indices):
            ds.indices = kept
            changed = True
    if changed:
        node._persist_data_streams()
