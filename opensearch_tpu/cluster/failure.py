"""Device failure detection: deterministic heartbeat over the device set.

Reference analog: `cluster/coordination/FollowersChecker.java` /
`LeaderChecker.java` — periodic pings with a consecutive-failure threshold
before a node is removed. Here the "followers" are accelerator chips: a
probe runs one tiny device computation AND FETCHES it (under the tunnel,
only a fetch proves the chip answered — a dispatched-but-unfetched op can
hang silently). The caller owns the clock: `tick()` is one heartbeat round
(a cron wrapper recovers the reference's scheduler), so tests and the
driver get reproducible failure sequences.

After `failure_threshold` CONSECUTIVE probe failures a device is declared
dead: every IndexService re-allocates its copies (promote surviving
replicas, rebuild moved ones — IndexService.fail_device), matching the
reference's allocation response to a left node."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


def default_prober(device) -> bool:
    import jax
    import jax.numpy as jnp
    import numpy as np
    try:
        out = jax.device_put(jnp.ones((8,), jnp.float32), device)
        return bool(np.asarray(out + 1.0).sum() == 16.0)
    except Exception:
        return False


class FailureDetector:
    def __init__(self, node, failure_threshold: int = 3,
                 prober: Optional[Callable] = None,
                 probe_timeout_s: float = 10.0):
        self.node = node
        self.failure_threshold = failure_threshold
        self.prober = prober or default_prober
        self.probe_timeout_s = probe_timeout_s
        self.consecutive: Dict[int, int] = {}
        self.dead: set = set()
        self.rounds = 0
        self.last_tick: Optional[float] = None

    def _probe_with_timeout(self, dev) -> bool:
        """A wedged chip HANGS the fetch rather than raising — exactly the
        case the probe exists for — so the probe runs on a watchdog thread
        and a timeout counts as a failure. The orphaned thread parks on the
        dead fetch; it is daemonic and costs one thread per hung probe."""
        import threading
        result = {"ok": False}

        def run():
            try:
                result["ok"] = bool(self.prober(dev))
            except Exception:
                result["ok"] = False
        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(self.probe_timeout_s)
        if t.is_alive():
            return False
        return result["ok"]

    def _devices(self) -> List:
        import jax
        return list(jax.devices())

    def tick(self) -> List[dict]:
        """One heartbeat round over live devices. Returns the events."""
        self.rounds += 1
        self.last_tick = time.time()
        events: List[dict] = []
        for ordinal, dev in enumerate(self._devices()):
            if ordinal in self.dead:
                continue
            ok = self._probe_with_timeout(dev)
            if ok:
                if self.consecutive.get(ordinal):
                    events.append({"device": ordinal, "event": "recovered",
                                   "after_failures":
                                       self.consecutive[ordinal]})
                self.consecutive[ordinal] = 0
                continue
            self.consecutive[ordinal] = self.consecutive.get(ordinal, 0) + 1
            events.append({"device": ordinal, "event": "probe_failed",
                           "consecutive": self.consecutive[ordinal]})
            if self.consecutive[ordinal] >= self.failure_threshold:
                self.dead.add(ordinal)
                events.append({"device": ordinal, "event": "failed"})
                for svc in self.node.indices.values():
                    svc.fail_device(ordinal)
        return events

    def stats(self) -> dict:
        return {"rounds": self.rounds, "dead_devices": sorted(self.dead),
                "failure_threshold": self.failure_threshold,
                "suspect": {str(k): v for k, v in self.consecutive.items()
                            if v > 0}}


class MemberFailureDetector:
    """Cross-node sibling of `FailureDetector`: tracks consecutive RPC /
    probe failures per cluster MEMBER and feeds the finding back into
    shard-copy selection (cluster/routing.py `order_copies`) instead of
    letting a dead member be rediscovered at RPC time on every request.

    A member past `failure_threshold` consecutive failures is
    DEPRIORITIZED — demoted to the back of every shard's copy preference
    list — not removed: it still serves shards that have no other copy,
    and one successful probe or RPC restores it (reference
    FollowersChecker semantics: suspicion is cheap to enter, cheap to
    leave). The caller owns the clock: RPC outcomes arrive via
    `note_failure`/`note_success`, and `tick(members)` runs one explicit
    probe round over the suspects so recovery is deterministic in tests.
    """

    def __init__(self, failure_threshold: int = 3,
                 prober: Optional[Callable] = None,
                 probe_timeout_s: float = 1.0):
        self.failure_threshold = int(failure_threshold)
        self.prober = prober            # (member, addr) -> bool
        self.probe_timeout_s = float(probe_timeout_s)
        self._lock = threading.Lock()
        self.consecutive: Dict[str, int] = {}
        self._depri: set = set()
        # remediation-pinned members (serving/remediator.py): demoted in
        # copy preference like suspicion-deprioritized ones, but a
        # successful probe/RPC does NOT clear a pin — only the actuator's
        # own TTL/green release (unpin) does, so a flapping member can't
        # immediately re-promote itself mid-remediation
        self._pinned: set = set()
        self.rounds = 0

    def note_failure(self, member: str) -> bool:
        """Record one failed RPC/probe. Returns True when this crossing
        newly deprioritized the member."""
        with self._lock:
            n = self.consecutive.get(member, 0) + 1
            self.consecutive[member] = n
            if n >= self.failure_threshold and member not in self._depri:
                self._depri.add(member)
                return True
        return False

    def note_success(self, member: str) -> None:
        with self._lock:
            self.consecutive[member] = 0
            self._depri.discard(member)

    def deprioritized(self) -> set:
        with self._lock:
            return set(self._depri) | set(self._pinned)

    def pin(self, member: str) -> bool:
        """Remediation engage: demote `member` in every shard's copy
        preference until `unpin` (the paired release — oslint OSL603).
        Returns True when this call newly pinned it."""
        with self._lock:
            if member in self._pinned:
                return False
            self._pinned.add(member)
            return True

    def unpin(self, member: str) -> None:
        with self._lock:
            self._pinned.discard(member)

    def pinned(self) -> set:
        with self._lock:
            return set(self._pinned)

    def _default_probe(self, member: str, addr: str) -> bool:
        import json
        import os
        import urllib.request
        headers = {}
        # same node-to-node trust as the RPC wire (`distnode._http`):
        # without the cluster token a security-enabled member answers
        # 403 and a demoted peer could never probe-recover
        tok = os.environ.get("OPENSEARCH_TPU_CLUSTER_TOKEN")
        if tok:
            headers["X-Cluster-Token"] = tok
        try:
            req = urllib.request.Request(f"http://{addr}/_internal/ping",
                                         method="GET", headers=headers)
            with urllib.request.urlopen(
                    req, timeout=self.probe_timeout_s) as r:
                return bool(json.loads(r.read().decode()).get("ok"))
        except Exception:
            return False

    def tick(self, members: Dict[str, str]) -> List[dict]:
        """One probe round over the currently-suspect members. A
        successful probe clears the suspicion (and the deprioritization);
        a failed one deepens it. Returns the events."""
        self.rounds += 1
        probe = self.prober or self._default_probe
        events: List[dict] = []
        with self._lock:
            suspects = set(self._depri) | {
                m for m, n in self.consecutive.items() if n > 0}
        for member in sorted(suspects):
            addr = members.get(member)
            if addr is None:
                continue
            if probe(member, addr):
                after = self.consecutive.get(member, 0)
                self.note_success(member)
                events.append({"member": member, "event": "recovered",
                               "after_failures": after})
            else:
                crossed = self.note_failure(member)
                events.append({"member": member, "event": "probe_failed",
                               "consecutive": self.consecutive[member],
                               **({"deprioritized": True}
                                  if crossed else {})})
        return events

    def stats(self) -> dict:
        with self._lock:
            return {"failure_threshold": self.failure_threshold,
                    "rounds": self.rounds,
                    "deprioritized": sorted(self._depri),
                    "pinned": sorted(self._pinned),
                    "suspect": {m: n for m, n in self.consecutive.items()
                                if n > 0}}
