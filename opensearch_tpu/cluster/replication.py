"""Segment replication + primary failover.

Reference `indices/replication/OngoingSegmentReplications.java` /
`SegmentReplicationTargetService` and the primary-promotion path in
`cluster/routing/allocation/`. The TPU translation: segments are immutable
host arrays re-hosted per device, so "copying segment files to the replica"
becomes `Segment.device_arrays(replica_device)` — a device_put of the same
arrays onto the replica's chip. Replicas never index; they sync the
primary's refreshed segment list at each checkpoint (refresh), exactly the
reference's NRT-segment-replication read path, and can be promoted to
primary by seeding a fresh Engine with their synced segments.
"""

from __future__ import annotations

from typing import List, Optional

from ..index.engine import DocLocation, Engine
from ..index.segment import Segment


class ReplicaShard:
    """A read-only shard copy at the last published checkpoint."""

    def __init__(self, primary: Engine, shard_id: int, replica_id: int,
                 device=None):
        self.primary = primary
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.device = device
        self.segments: List[Segment] = []
        self.checkpoint = -1       # primary seq_no this copy has synced to
        self.state = "STARTED"

    def sync(self, warm: bool = True) -> None:
        """Publish checkpoint: adopt the primary's current segment list and
        (optionally) re-host the arrays on this copy's device now rather
        than at first search."""
        self.segments = list(self.primary.segments)
        self.checkpoint = self.primary.seq_no
        if warm and self.device is not None:
            for seg in self.segments:
                seg.device_arrays(self.device)

    @property
    def num_docs(self) -> int:
        return sum(s.live_count for s in self.segments)


def promote_to_primary(mappings, replica: ReplicaShard,
                       primary_term: int) -> Engine:
    """Build a fresh primary Engine over the replica's synced segments
    (reference: replica promotion replays the safe commit; with segment
    replication the synced segments ARE the safe commit)."""
    eng = Engine(mappings, primary_term=primary_term)
    eng.segments = list(replica.segments)
    seq = -1
    for seg in eng.segments:
        for local, doc_id in enumerate(seg.ids):
            s = int(seg.seq_nos[local])
            seq = max(seq, s)
            if seg.live[local]:
                cur = eng.version_map.get(doc_id)
                if cur is None or s >= cur.seq_no:
                    eng.version_map[doc_id] = DocLocation(
                        s, in_buffer=False, segment=seg, local_doc=local)
    eng.seq_no = seq
    # keep fresh segment names unique under the new primary
    for seg in eng.segments:
        num = int(seg.name.lstrip("_m").lstrip("_") or 0)
        eng._seg_counter = max(eng._seg_counter, num + 1)
    return eng
