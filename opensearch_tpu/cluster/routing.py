"""Shard routing. Analog of reference
`cluster/routing/OperationRouting.java` + `cluster/routing/Murmur3HashFunction.java`:
shard = floorMod(murmur3_x86_32(routing_string), num_shards).
"""

from __future__ import annotations


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF


def murmur3_x86_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86_32 (same algorithm/seed as the reference's
    Murmur3HashFunction, which hashes the UTF-16LE... actually the reference
    hashes the String's UTF-16 code units via StringHelper on UTF-8 bytes of
    the id; we standardize on UTF-8 bytes — consistent within this engine)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    nblocks = len(data) // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4:(i + 1) * 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[nblocks * 4:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def assign_copies(num_shards: int, members, n_copies: int):
    """Shard -> ordered copy list (primary first), round-robin over the
    sorted member names with each subsequent copy on the next distinct
    member — the compact analog of the reference's balanced allocator.
    `n_copies` is clamped to the member count (a copy per member at
    most)."""
    order = sorted(members)
    n = max(1, min(int(n_copies), len(order)))
    return {s: [order[(s + i) % len(order)] for i in range(n)]
            for s in range(num_shards)}


def order_copies(copies, deprioritized):
    """Per-request copy preference: the configured order (primary first)
    with members the failure detector currently deprioritizes demoted to
    the back, original order preserved within each class. Deterministic —
    a recovered path must pick the same replica every time so the parity
    harness can hold it byte-identical."""
    depri = [m for m in copies if m in deprioritized]
    return [m for m in copies if m not in deprioritized] + depri


def shard_for(routing: str, num_shards: int) -> int:
    from .. import native
    if native.available():
        h = native.murmur3(routing.encode("utf-8"))
    else:
        h = murmur3_x86_32(routing.encode("utf-8"))
    # Java floorMod on the signed 32-bit value
    signed = h - (1 << 32) if h >= (1 << 31) else h
    return signed % num_shards
