"""Cluster coordination: leader election + two-phase state publication.

Reference analog: `cluster/coordination/Coordinator.java`,
`ElectionStrategy`, `CoordinationState`, `PublicationTransportHandler` —
term-based voting with quorum, then PUBLISH -> COMMIT of the cluster
state to followers.

The deployment model here is in-process peer Nodes (the same peers
cross-cluster search reaches), and like the lifecycle/failure-detector
services the caller owns the clock: every transition is a deterministic
method call, so election storms, quorum loss, partitions and stale-term
publications are all unit-testable without timers or sockets. A real
multi-host process story would put jax.distributed process groups under
the same state machine; the protocol logic is host-side either way and
does not touch the device path.

Election rule (reference ElectionStrategy default): among live
master-eligible nodes, the candidate with the FRESHEST accepted state —
highest (term, version) — wins, node name as the deterministic
tiebreak. A candidate needs votes from a MAJORITY of all master-eligible
nodes (not just live ones), so a minority partition can never elect."""

from __future__ import annotations

import copy
from typing import Dict, List, Optional


class CoordinationError(Exception):
    pass


class ClusterCoordinator:
    def __init__(self, nodes: List):
        if not nodes:
            raise CoordinationError("coordinator needs at least one node")
        names = [n.node_name for n in nodes]
        if len(set(names)) != len(names):
            raise CoordinationError("duplicate node names")
        self.nodes: Dict[str, object] = {n.node_name: n for n in nodes}
        self.live: set = set(names)
        self.term = 0
        self.leader: Optional[str] = None
        # per-node accepted (term, version) — freshness for the election
        self.accepted: Dict[str, tuple] = {name: (0, 0) for name in names}
        self.history: List[dict] = []

    # ---------------- membership ----------------

    def fail_node(self, name: str) -> None:
        if name not in self.nodes:
            raise CoordinationError(f"unknown node [{name}]")
        self.live.discard(name)
        if self.leader == name:
            self.leader = None
            self.history.append({"event": "leader_lost", "node": name})

    def heal_node(self, name: str) -> None:
        if name not in self.nodes:
            raise CoordinationError(f"unknown node [{name}]")
        self.live.add(name)

    def quorum(self) -> int:
        return len(self.nodes) // 2 + 1

    def has_quorum(self) -> bool:
        return len(self.live) >= self.quorum()

    # ---------------- election ----------------

    def elect(self) -> Optional[str]:
        """One election round. Returns the leader name, or None when no
        quorum exists (the cluster stays leaderless — reference behavior
        under lost majority)."""
        if not self.has_quorum():
            self.leader = None
            self.history.append({"event": "election_failed",
                                 "reason": "no_quorum",
                                 "live": sorted(self.live)})
            return None
        # freshest accepted state wins; name is the deterministic tiebreak
        candidate = max(self.live, key=lambda n: (self.accepted[n], n))
        self.term += 1
        self.leader = candidate
        self.history.append({"event": "elected", "leader": candidate,
                             "term": self.term})
        return candidate

    def ensure_leader(self) -> Optional[str]:
        # a leader that lost its majority steps down (reference
        # Coordinator.becomeCandidate on quorum loss)
        if (self.leader is not None and self.leader in self.live
                and self.has_quorum()):
            return self.leader
        return self.elect()

    # ---------------- state publication ----------------

    def publish(self, from_node: Optional[str] = None) -> dict:
        """Two-phase publish of the leader's cluster metadata: PUBLISH to
        every live follower, COMMIT once a quorum (leader included) has
        accepted. Stale-term publishers are rejected (a deposed leader
        cannot overwrite newer state)."""
        src = from_node if from_node is not None else self.leader
        if src is None:
            raise CoordinationError("no leader to publish from")
        if src != self.leader:
            raise CoordinationError(
                f"[{src}] is not the current leader (term {self.term})")
        if src not in self.live:
            raise CoordinationError(f"leader [{src}] is not live")
        leader_node = self.nodes[src]
        version = leader_node.metadata.version
        # phase 1: PUBLISH — determine who can accept, check quorum BEFORE
        # any acceptance is recorded (a failed publish must leave no
        # follower claiming freshness for state it never received)
        targets = [src] + sorted(self.live - {src})
        if len(targets) < self.quorum():
            raise CoordinationError(
                f"publish failed: {len(targets)} acks < quorum "
                f"{self.quorum()}")
        # phase 2: COMMIT — install the state, recording acceptance
        # together with the installation (atomically per follower)
        for name in targets:
            if name != src:
                follower = self.nodes[name]
                follower.metadata.indices = copy.deepcopy(
                    leader_node.metadata.indices)
                follower.metadata.aliases = copy.deepcopy(
                    leader_node.metadata.aliases)
                follower.metadata.templates = copy.deepcopy(
                    leader_node.metadata.templates)
                follower.metadata.version = version
            self.accepted[name] = (self.term, version)
        self.history.append({"event": "published", "term": self.term,
                             "version": version, "acks": len(targets)})
        return {"term": self.term, "version": version,
                "committed": targets}

    def stats(self) -> dict:
        return {"term": self.term, "leader": self.leader,
                "nodes": sorted(self.nodes), "live": sorted(self.live),
                "quorum": self.quorum(),
                "has_quorum": self.has_quorum()}
