"""Two-process cluster: full Nodes in separate OS processes, one index.

The product promotion of the r4 two-process SPMD experiment
(`tests/_mh_child.py`): each process runs a complete Node + RestClient +
HttpServer; cluster membership, state publication, and the search
scatter/gather all travel over the HTTP wire layer — the analog of the
reference's netty transport + coordinator
(`modules/transport-netty4/src/main/java/org/opensearch/transport/netty4/
Netty4Transport.java:1`, `server/src/main/java/org/opensearch/cluster/
coordination/Coordinator.java:1`, fan-out per
`action/search/TransportSearchAction.java:1`).

Design (primaries-only v1, documented):

- **Membership**: the seed node is the cluster manager. A joiner POSTs
  `/_internal/join`; the manager records it and publishes the full cluster
  state (term/version, members, per-index shard routing) to every member —
  the two-phase publish collapsed to one trusted-wire RPC.
- **Routing**: `create_index` assigns each shard an owner round-robin over
  the sorted member names. Every member creates the SAME index locally
  (same num_shards); only the owner's copy of a shard ever receives
  documents, so non-owned local shards stay empty and contribute nothing
  to that node's local scatter leg.
- **Writes**: a doc routes by `cluster.routing.shard_for(id)`; the
  coordinator forwards non-local docs to the owner's PUBLIC HTTP doc
  endpoint (the wire is the product wire, not a side channel).
- **Search = DFS_QUERY_THEN_FETCH over HTTP** (reference
  `search/dfs/DfsSearchResult.java:1` semantics):
    1. DFS: every node reports the collection statistics its own rewrite
       of the query consumes (df / collection_tf / field doc_count+sum_dl /
       maxDoc), via a recording stats context; the coordinator sums them.
    2. QUERY: every node runs its local per-shard query phase with a
       GlobalStatsContext pinned to the summed statistics — scores are
       therefore IDENTICAL to a single node holding all the data.
    3. The coordinator reduces once (`reduce_shard_results`) and
    4. FETCH: hydrates winning docs from their owning nodes.
  Internal RPC payloads are pickled (base64 in a JSON envelope) — typed
  agg partials and sort values cross the wire losslessly; the reference's
  transport is binary object serialization for the same reason. The
  `/_internal/*` surface is a trusted node-to-node wire (security is a
  declared exclusion, SURVEY §2.9).
- **Failure**: a dead member fails only ITS shards — the coordinator
  serves partial results and reports `_shards.failed` (reference
  allow_partial_search_results=true default). The kill-one-node test
  (`tests/test_distnode.py`) asserts the survivor keeps serving its
  shards' data.

Unsupported on a distributed index (explicit 400, never silently wrong):
non-`_score` sorts, collapse, rescore, search_after/scroll/PIT, suggest,
profile, knn, and aggregations with sub-aggregations (their coordinator
refinement needs cross-node sub-searches; reference parity for those is
future work).
"""

from __future__ import annotations

import base64
import contextlib
import json
import os
import pickle
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..rest.client import ApiError, RestClient
from ..rest.http_server import HttpServer
from ..search import compiler as C
from ..search import query_dsl as dsl
from ..search.aggregations import parse_aggs
from ..search.executor import (Candidate, ShardQueryResult,
                               _global_stats_contexts, reduce_shard_results)
from .node import Node
from .routing import shard_for

_RPC_TIMEOUT_S = 30.0


# ---------------------------------------------------------------------
# statistics contexts for the cross-node DFS phase
# ---------------------------------------------------------------------

class RecordingStatsContext(C.ShardContext):
    """Wraps the local collection-stats view and records every statistic
    the query rewrite consumes — the node-local half of the DFS phase."""

    def __init__(self, mappings, segments, similarity=None,
                 field_similarities=None):
        super().__init__(mappings, segments, similarity, field_similarities)
        self.rec = {"num_docs": 0, "df": {}, "ctf": {}, "fs": {}}

    @property
    def num_docs(self) -> int:
        n = C.ShardContext.num_docs.fget(self)
        self.rec["num_docs"] = n
        return n

    def doc_freq(self, field: str, term: str) -> int:
        v = super().doc_freq(field, term)
        self.rec["df"][(field, term)] = v
        return v

    def collection_tf(self, field: str, term: str) -> float:
        v = super().collection_tf(field, term)
        self.rec["ctf"][(field, term)] = v
        return v

    def field_stats(self, field: str) -> Tuple[int, int]:
        v = super().field_stats(field)
        self.rec["fs"][field] = v
        return v


class GlobalStatsContext(C.ShardContext):
    """A stats context pinned to coordinator-summed global statistics: every
    node scores with the same idf/avgdl no matter where documents live.
    Statistics the DFS recording did not capture (rare: a fetch-side
    feature asking about a term the query rewrite never touched) fall back
    to local values — degraded, never crashing."""

    def __init__(self, mappings, segments, similarity, field_similarities,
                 g: dict):
        super().__init__(mappings, segments, similarity, field_similarities)
        self._g = g

    @property
    def num_docs(self) -> int:
        return self._g["num_docs"]

    def doc_freq(self, field: str, term: str) -> int:
        v = self._g["df"].get((field, term))
        return v if v is not None else super().doc_freq(field, term)

    def collection_tf(self, field: str, term: str) -> float:
        v = self._g["ctf"].get((field, term))
        return v if v is not None else super().collection_tf(field, term)

    def field_stats(self, field: str) -> Tuple[int, int]:
        v = self._g["fs"].get(field)
        return tuple(v) if v is not None else super().field_stats(field)


def _merge_dfs(parts: List[dict]) -> dict:
    g = {"num_docs": 0, "df": {}, "ctf": {}, "fs": {}}
    for p in parts:
        g["num_docs"] += p["num_docs"]
        for k, v in p["df"].items():
            g["df"][k] = g["df"].get(k, 0) + v
        for k, v in p["ctf"].items():
            g["ctf"][k] = g["ctf"].get(k, 0.0) + v
        for k, (dc, sdl) in p["fs"].items():
            odc, osdl = g["fs"].get(k, (0, 0))
            g["fs"][k] = (odc + dc, osdl + sdl)
    return g


# ---------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------

def _b64(obj) -> str:
    return base64.b64encode(pickle.dumps(obj)).decode("ascii")


def _unb64(s: str):
    return pickle.loads(base64.b64decode(s.encode("ascii")))


def _http(addr: str, method: str, path: str, payload=None,
          timeout: float = _RPC_TIMEOUT_S) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"}
    # shared-secret node-to-node trust: when the cluster runs with REST
    # security enabled, every /_internal call must carry this token (the
    # compact analog of the reference's transport-layer TLS mutual auth)
    tok = os.environ.get("OPENSEARCH_TPU_CLUSTER_TOKEN")
    if tok:
        headers["X-Cluster-Token"] = tok
    req = urllib.request.Request(
        f"http://{addr}{path}", data=data, method=method,
        headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        raw = r.read().decode()
    return json.loads(raw) if raw else {}


class NodeUnreachable(Exception):
    pass


# ---------------------------------------------------------------------
# the distributed node
# ---------------------------------------------------------------------

class DistClusterNode:
    """A full Node + HTTP server participating in a multi-process cluster.

    Public surface: `create_index`, `index_doc`, `refresh`, `search`,
    `get`, `cluster_state`, `stop`. Everything travels over HTTP — this
    object is also the handler for `/_internal/*` RPCs on its server.
    """

    def __init__(self, name: str, seed: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self.node = Node()
        self.client = RestClient(node=self.node)
        self.server = HttpServer(self.client, host=host, port=port)
        self.server.dist = self
        self.port = self.server.start()
        self.addr = f"{host}:{self.port}"
        self._lock = threading.RLock()
        # cluster state (reference ClusterState: term/version + routing)
        self.term = 1
        self.version = 0
        self.leader = name if seed is None else None
        self.members: Dict[str, str] = {name: self.addr}
        self.routing: Dict[str, Dict[int, str]] = {}   # index -> shard -> node
        self.index_bodies: Dict[str, dict] = {}
        if seed is not None:
            st = _http(seed, "POST", "/_internal/join",
                       {"name": name, "addr": self.addr})
            self._apply_state(st["state"])

    # ---------------- state machine ----------------

    def _state(self) -> dict:
        return {"term": self.term, "version": self.version,
                "leader": self.leader, "members": self.members,
                "routing": {i: {str(s): n for s, n in r.items()}
                            for i, r in self.routing.items()},
                "index_bodies": self.index_bodies}

    def _apply_state(self, st: dict) -> None:
        with self._lock:
            self.term = st["term"]
            self.version = st["version"]
            self.leader = st["leader"]
            self.members = dict(st["members"])
            self.routing = {i: {int(s): n for s, n in r.items()}
                            for i, r in st["routing"].items()}
            self.index_bodies = dict(st["index_bodies"])
            # idempotently materialize any index this node doesn't have yet
            for iname, body in self.index_bodies.items():
                if iname not in self.node.indices:
                    self.client.indices.create(iname, body)

    def _publish(self) -> None:
        """Leader: bump version, push full state to every member (self
        applies synchronously). Unreachable members keep their shards in
        the routing table; searches report them failed until they rejoin."""
        # bump + snapshot under the (reentrant) state lock: the unlocked
        # bump raced `_apply_state`'s locked `self.version = st["version"]`
        with self._lock:
            self.version += 1
            st = self._state()
        for name, addr in list(self.members.items()):
            if name == self.name:
                continue
            try:
                _http(addr, "POST", "/_internal/publish", {"state": st})
            except (urllib.error.URLError, OSError):
                pass

    # ---------------- internal RPC handler (called by HttpServer) --------

    def handle_internal(self, method: str, parts: List[str], body: dict
                        ) -> Tuple[int, dict]:
        op = parts[1] if len(parts) > 1 else ""
        if op == "join" and method == "POST":
            with self._lock:
                self.members[body["name"]] = body["addr"]
                self._publish()
                return 200, {"state": self._state()}
        if op == "publish" and method == "POST":
            self._apply_state(body["state"])
            return 200, {"acknowledged": True}
        if op == "dfs" and method == "POST":
            with self._rpc_span("dist.dfs", body) as s, \
                    self._rpc_timeline("dfs", body) as rtl:
                rec = self._local_dfs(body["index"], body["body"])
            return 200, {"rec": _b64(rec), "span": self._span_out(s),
                         "obs": self._obs_out(rtl)}
        if op == "query_phase" and method == "POST":
            with self._rpc_span("dist.query_phase", body) as s, \
                    self._rpc_timeline("query_phase", body) as rtl:
                results = self._local_query(body["index"], body["body"],
                                            _unb64(body["g"]))
            return 200, {"results": _b64(results),
                         "span": self._span_out(s),
                         "obs": self._obs_out(rtl)}
        if op == "fetch_phase" and method == "POST":
            with self._rpc_span("dist.fetch_phase", body) as s, \
                    self._rpc_timeline("fetch_phase", body) as rtl:
                hits = self._local_fetch(body["index"], body["body"],
                                         int(body["shard"]),
                                         _unb64(body["cands"]),
                                         _unb64(body["g"]))
            return 200, {"hits": _b64(hits), "span": self._span_out(s),
                         "obs": self._obs_out(rtl)}
        if op == "state" and method == "GET":
            return 200, {"state": self._state()}
        if op == "create_index" and method == "POST":
            return 200, self.create_index(parts[2], body)
        if op == "search" and method == "POST":
            # run a DISTRIBUTED search coordinated by THIS node (any member
            # can coordinate, like any reference node with the coordinator
            # role)
            return 200, self.search(body["index"], body["body"])
        return 404, {"error": {"type": "resource_not_found_exception",
                               "reason": f"unknown internal op [{op}]"}}

    # ---------------- trace propagation over the wire ----------------
    #
    # The coordinator stamps every /_internal RPC payload with its trace
    # context (`trace_ctx`); the serving node runs the local phase under a
    # span carrying that context and RETURNS the finished span tree in
    # the response, which the coordinator grafts under its own phase span
    # (`TRACER.attach_remote`) — so one distributed search reads as ONE
    # coherent parent-child trace on the coordinating node, while each
    # member's ring still holds its local half, attributable via the
    # stamped parent ids.

    def _rpc_span(self, name: str, body: dict):
        from ..utils.trace import TRACER
        tctx = body.get("trace_ctx") or {}
        return TRACER.span(name, node=self.name,
                           **{k: tctx[k] for k in
                              ("trace_root_id", "parent_span_id",
                               "coordinator") if k in tctx})

    @staticmethod
    def _span_out(s) -> Optional[dict]:
        return s.to_dict() if s is not None else None

    # ---------------- flight-recorder stitching over the wire ---------
    #
    # Mirrors the trace propagation above: the coordinator stamps its
    # (node, timeline) onto every RPC; the serving node runs the local
    # phase under its OWN timeline carrying the origin linkage, and the
    # response returns that timeline's events, which the coordinator
    # grafts into the request's journal (`RECORDER.graft`) — so one
    # distributed search reads as ONE stitched cross-node timeline.

    @contextlib.contextmanager
    def _rpc_timeline(self, op: str, body: dict):
        from ..obs import flight_recorder as _fr
        ctx = body.get("obs_ctx")
        if not _fr.RECORDER.enabled or not isinstance(ctx, dict):
            yield 0
            return
        tl = _fr.RECORDER.start(f"rpc.{op}", node=self.name,
                                origin_node=ctx.get("node"),
                                origin_timeline=ctx.get("timeline"))
        token = _fr.set_current(tl)
        try:
            if tl:
                _fr.RECORDER.record(tl, "rpc.accept", op=op,
                                    node=self.name)
            yield tl
        finally:
            _fr.reset_current(token)

    @staticmethod
    def _obs_out(tl: int) -> Optional[list]:
        if not tl:
            return None
        from ..obs import flight_recorder as _fr
        return _fr.RECORDER.timeline_events(tl)

    def _rpc(self, member: str, op: str, payload: dict) -> dict:
        """Coordinator-side RPC with trace stamping + span grafting +
        flight-recorder timeline stitching + latency accounting."""
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        from ..utils.trace import TRACER
        wctx = TRACER.wire_context()
        if wctx is not None:
            payload = dict(payload,
                           trace_ctx=dict(wctx, coordinator=self.name))
        tl = _fr.current() if _fr.RECORDER.enabled else 0
        if tl:
            payload = dict(payload,
                           obs_ctx={"node": self.name, "timeline": tl})
        t0 = time.monotonic()
        try:
            r = _http(self.members[member], "POST", f"/_internal/{op}",
                      payload)
        except Exception:
            METRICS.counter("dist.rpc.failed").inc()
            if tl:
                _fr.RECORDER.record(tl, "rpc.failed", op=op, node=member)
            raise
        METRICS.histogram(f"dist.rpc.{op}").record(
            (time.monotonic() - t0) * 1000.0)
        TRACER.attach_remote(r.get("span"))
        _fr.RECORDER.graft(tl, r.get("obs"), node=member)
        return r

    # ---------------- cluster API ----------------

    def cluster_state(self) -> dict:
        return self._state()

    def create_index(self, name: str, body: dict) -> dict:
        """Leader-only (forwarded if called on a follower): create on every
        member, assign shard owners round-robin over sorted member names."""
        if self.leader != self.name:
            return _http(self.members[self.leader], "POST",
                         f"/_internal/create_index/{name}", body)
        with self._lock:
            self.client.indices.create(name, body)
            n_shards = self.node.indices[name].meta.num_shards
            order = sorted(self.members)
            self.routing[name] = {s: order[s % len(order)]
                                  for s in range(n_shards)}
            self.index_bodies[name] = body
            for mname, addr in self.members.items():
                if mname == self.name:
                    continue
                _http(addr, "PUT", f"/{name}", body)
            self._publish()
        return {"acknowledged": True, "index": name,
                "routing": self.routing[name]}

    def index_doc(self, index: str, doc: dict, id: str,
                  refresh: bool = False) -> dict:
        """Route by doc id; forward non-local docs to the owner's public
        doc endpoint."""
        owner = self._owner(index, id)
        refresh_q = "?refresh=true" if refresh else ""
        if owner == self.name:
            return self.client.index(index, doc, id=id, refresh=refresh)
        return _http(self.members[owner], "PUT",
                     f"/{index}/_doc/{id}{refresh_q}", doc)

    def get(self, index: str, id: str) -> dict:
        owner = self._owner(index, id)
        if owner == self.name:
            return self.client.get(index, id)
        try:
            return _http(self.members[owner], "GET", f"/{index}/_doc/{id}")
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, "resource_not_found_exception",
                           f"[{id}] not found")

    def refresh(self, index: str) -> None:
        self.client.indices.refresh(index)
        for mname, addr in self.members.items():
            if mname == self.name:
                continue
            try:
                _http(addr, "POST", f"/{index}/_refresh")
            except (urllib.error.URLError, OSError):
                pass

    def _owner(self, index: str, id: str) -> str:
        r = self.routing.get(index)
        if r is None:
            raise ApiError(404, "index_not_found_exception",
                           f"no such index [{index}]")
        n = self.node.indices[index].meta.num_shards
        return r[shard_for(id, n)]

    # ---------------- distributed search ----------------

    _UNSUPPORTED = ("collapse", "rescore", "search_after", "suggest",
                    "profile", "knn", "scroll", "pit")

    def _check_supported(self, body: dict) -> List:
        for k in self._UNSUPPORTED:
            if body.get(k):
                raise ApiError(400, "illegal_argument_exception",
                               f"[{k}] is not supported on a distributed "
                               f"index")
        for s in body.get("sort", []):
            f = s if isinstance(s, str) else next(iter(s))
            if f != "_score":
                raise ApiError(400, "illegal_argument_exception",
                               "only _score sort is supported on a "
                               "distributed index")
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        for an in (agg_nodes or []):
            if an.subs:
                raise ApiError(400, "illegal_argument_exception",
                               "sub-aggregations are not supported on a "
                               "distributed index")
        return agg_nodes or []

    def _local_dfs(self, index: str, body: dict) -> dict:
        svc = self.node.indices[index]
        searchers = svc.searchers
        segs = [g for s in searchers for g in s.engine.segments]
        ctx = RecordingStatsContext(svc.mappings, segs, svc.default_sim,
                                    getattr(svc, "field_similarities", None))
        try:
            from ..search.executor import _collect_named
            lroot = C.rewrite(dsl.parse_query(body.get("query")), ctx,
                              scoring=True)
            # named queries are fetch-side state that does not cross the
            # wire yet; piggyback the check on the rewrite DFS already does
            ctx.rec["named"] = bool(_collect_named(lroot))
        except dsl.QueryParseError:
            pass
        _ = ctx.num_docs          # maxDoc is always part of the DFS result
        # avgdl (per-field doc_count + sum_dl) is consumed at the prepare
        # stage, not rewrite — record it for every text field this node
        # holds so the merged fs covers whatever the query touches
        for s in segs:
            for f in s.text_stats:
                ctx.field_stats(f)
        return ctx.rec

    def _global_ctx(self, index: str, g: dict) -> GlobalStatsContext:
        svc = self.node.indices[index]
        segs = [s for sr in svc.searchers for s in sr.engine.segments]
        return GlobalStatsContext(svc.mappings, segs, svc.default_sim,
                                  getattr(svc, "field_similarities", None),
                                  g)

    def _local_query(self, index: str, body: dict, g: dict
                     ) -> List[ShardQueryResult]:
        """Per-shard query phase with global stats; results stripped of
        segment references (they do not cross the wire)."""
        svc = self.node.indices[index]
        ctx = self._global_ctx(index, g)
        out = []
        for i, s in enumerate(svc.searchers):
            r = s.query_phase(dict(body), shard_ord=i, stats_ctx=ctx)
            r.segments = []        # host-local only
            r.named_by_doc = {}
            out.append(r)
        return out

    def _local_fetch(self, index: str, body: dict, shard: int,
                     cands: List[tuple], g: dict) -> List[dict]:
        svc = self.node.indices[index]
        s = svc.searchers[shard]
        segs = (list(s.replica.segments) if s.replica is not None
                else list(s.engine.segments))
        result = ShardQueryResult(shard=shard, segments=segs)
        sel = [Candidate(shard, so, ld, sc, tuple(sv), tuple(rv))
               for so, ld, sc, sv, rv in cands]
        return s.fetch_phase(result, sel, dict(body),
                             stats_ctx=self._global_ctx(index, g))

    def search(self, index: str, body: dict) -> dict:
        """Distributed DFS_QUERY_THEN_FETCH across every member, reduced
        once on this node. The whole scatter/gather runs under ONE root
        span; every remote leg's span tree comes back on the RPC response
        and nests under the coordinator's phase span. Same deal for the
        flight recorder: the coordinator owns one timeline, every RPC
        carries it, and the remote legs' events graft back into it."""
        from ..obs import flight_recorder as _fr
        from ..utils.trace import TRACER
        token = None
        if _fr.RECORDER.enabled and not _fr.current():
            tl = _fr.RECORDER.start("dist.search", index=index,
                                    node=self.name)
            token = _fr.set_current(tl)
        try:
            with TRACER.span("dist.search", index=index,
                             coordinator=self.name):
                if _fr.RECORDER.enabled and _fr.current():
                    _fr.RECORDER.record(_fr.current(), "dist.accept",
                                        index=index,
                                        coordinator=self.name)
                return self._search_traced(index, body)
        finally:
            if token is not None:
                _fr.reset_current(token)

    def _search_traced(self, index: str, body: dict) -> dict:
        from ..utils.metrics import METRICS
        from ..utils.trace import TRACER
        t0 = time.monotonic()
        agg_nodes = self._check_supported(body)
        svc = self.node.indices.get(index)
        if svc is None:
            raise ApiError(404, "index_not_found_exception",
                           f"no such index [{index}]")
        n_shards = svc.meta.num_shards
        owners = self.routing.get(index, {s: self.name
                                          for s in range(n_shards)})
        remote_members = sorted({n for n in owners.values()
                                 if n != self.name})

        # --- phase 1: DFS (collection statistics from every node)
        dead: List[str] = []
        with TRACER.span("dist.dfs", nodes=1 + len(remote_members)), \
                METRICS.timer("dist.dfs"):
            parts = [self._local_dfs(index, body)]
            if parts[0].get("named"):
                raise ApiError(400, "illegal_argument_exception",
                               "named queries (_name) are not supported "
                               "on a distributed index")
            for m in remote_members:
                try:
                    r = self._rpc(m, "dfs", {"index": index, "body": body})
                    parts.append(_unb64(r["rec"]))
                except (urllib.error.URLError, OSError, KeyError):
                    dead.append(m)
        g = _merge_dfs(parts)

        # --- phase 2: QUERY everywhere with pinned global stats
        remote_results: Dict[int, ShardQueryResult] = {}
        with TRACER.span("dist.query", nodes=1 + len(remote_members)), \
                METRICS.timer("dist.query"):
            results = self._local_query(index, body, g)
            for m in remote_members:
                if m in dead:
                    continue
                try:
                    r = self._rpc(m, "query_phase",
                                  {"index": index, "body": body,
                                   "g": _b64(g)})
                    for sr in _unb64(r["results"]):
                        # only the owner's copy of a shard carries data;
                        # the coordinator keeps the owned legs and drops
                        # empty non-owned duplicates
                        if owners.get(sr.shard) == m:
                            remote_results[sr.shard] = sr
                except (urllib.error.URLError, OSError, KeyError):
                    dead.append(m)
        merged: List[ShardQueryResult] = []
        failed_shards = []
        for s in range(n_shards):
            owner = owners.get(s, self.name)
            if owner == self.name:
                merged.append(results[s])
            elif s in remote_results:
                merged.append(remote_results[s])
            else:
                failed_shards.append((s, owner))

        with TRACER.span("dist.reduce", shards=len(merged)):
            reduced = reduce_shard_results(merged, body,
                                           agg_nodes=agg_nodes)

        # --- phase 3: FETCH winners from their owning nodes
        by_shard: Dict[int, List[Candidate]] = {}
        for c in reduced["selected"]:
            by_shard.setdefault(c.shard, []).append(c)
        hits_by_key: Dict[Tuple, dict] = {}
        with TRACER.span("dist.fetch", shards=len(by_shard)), \
                METRICS.timer("dist.fetch"):
            for s_id, sel in by_shard.items():
                owner = owners.get(s_id, self.name)
                if owner == self.name:
                    sr = self.node.indices[index].searchers[s_id]
                    segs = (list(sr.replica.segments)
                            if sr.replica is not None
                            else list(sr.engine.segments))
                    res = ShardQueryResult(shard=s_id, segments=segs)
                    fetched = sr.fetch_phase(
                        res, sel, dict(body),
                        stats_ctx=self._global_ctx(index, g))
                else:
                    cands = [(c.seg_ord, c.local_doc, c.score,
                              list(c.sort_values), list(c.raw_sort_values))
                             for c in sel]
                    try:
                        r = self._rpc(owner, "fetch_phase",
                                      {"index": index, "body": body,
                                       "shard": s_id, "cands": _b64(cands),
                                       "g": _b64(g)})
                        fetched = _unb64(r["hits"])
                    except (urllib.error.URLError, OSError, KeyError):
                        # the owner died BETWEEN query and fetch: this
                        # shard's winners can no longer be hydrated —
                        # report the shard failed instead of silently
                        # returning fewer hits
                        failed_shards.append((s_id, owner))
                        fetched = []
                for c, h in zip(sel, fetched):
                    hits_by_key[(c.shard, c.seg_ord, c.local_doc)] = h
        hits = [hits_by_key[(c.shard, c.seg_ord, c.local_doc)]
                for c in reduced["selected"]
                if (c.shard, c.seg_ord, c.local_doc) in hits_by_key]
        for h in hits:
            h["_index"] = index

        track = body.get("track_total_hits", True)
        total, relation = reduced["total"], reduced.get("total_rel", "eq")
        if track is not True and track is not False:
            track_n = int(track)
            if total > track_n:
                total, relation = track_n, "gte"
        resp = {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": False,
            "_shards": {"total": n_shards,
                        "successful": n_shards - len(failed_shards),
                        "skipped": 0, "failed": len(failed_shards),
                        **({"failures": [
                            {"shard": s, "node": n,
                             "reason": {"type": "node_unreachable"}}
                            for s, n in failed_shards]}
                           if failed_shards else {})},
            "hits": {"total": {"value": total, "relation": relation},
                     "max_score": (reduced["max_score"]
                                   if reduced["max_score"] != float("-inf")
                                   else None),
                     "hits": hits},
        }
        if reduced["aggs"]:
            resp["aggregations"] = reduced["aggs"]
        return resp

    # ---------------- lifecycle ----------------

    def stop(self) -> None:
        self.server.stop()
