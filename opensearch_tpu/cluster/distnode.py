"""Two-process cluster: full Nodes in separate OS processes, one index.

The product promotion of the r4 two-process SPMD experiment
(`tests/_mh_child.py`): each process runs a complete Node + RestClient +
HttpServer; cluster membership, state publication, and the search
scatter/gather all travel over the HTTP wire layer — the analog of the
reference's netty transport + coordinator
(`modules/transport-netty4/src/main/java/org/opensearch/transport/netty4/
Netty4Transport.java:1`, `server/src/main/java/org/opensearch/cluster/
coordination/Coordinator.java:1`, fan-out per
`action/search/TransportSearchAction.java:1`).

Design (primaries-only v1, documented):

- **Membership**: the seed node is the cluster manager. A joiner POSTs
  `/_internal/join`; the manager records it and publishes the full cluster
  state (term/version, members, per-index shard routing) to every member —
  the two-phase publish collapsed to one trusted-wire RPC.
- **Routing**: `create_index` assigns each shard an owner round-robin over
  the sorted member names. Every member creates the SAME index locally
  (same num_shards); only the owner's copy of a shard ever receives
  documents, so non-owned local shards stay empty and contribute nothing
  to that node's local scatter leg.
- **Writes**: a doc routes by `cluster.routing.shard_for(id)`; the
  coordinator forwards non-local docs to the owner's PUBLIC HTTP doc
  endpoint (the wire is the product wire, not a side channel).
- **Search = DFS_QUERY_THEN_FETCH over HTTP** (reference
  `search/dfs/DfsSearchResult.java:1` semantics):
    1. DFS: every node reports the collection statistics its own rewrite
       of the query consumes (df / collection_tf / field doc_count+sum_dl /
       maxDoc), via a recording stats context; the coordinator sums them.
    2. QUERY: every node runs its local per-shard query phase with a
       GlobalStatsContext pinned to the summed statistics — scores are
       therefore IDENTICAL to a single node holding all the data.
    3. The coordinator reduces once (`reduce_shard_results`) and
    4. FETCH: hydrates winning docs from their owning nodes.
  Internal RPC payloads are pickled (base64 in a JSON envelope) — typed
  agg partials and sort values cross the wire losslessly; the reference's
  transport is binary object serialization for the same reason. The
  `/_internal/*` surface is a trusted node-to-node wire (security is a
  declared exclusion, SURVEY §2.9).
- **Failure domain** (docs/RESILIENCE.md): every `/_internal` RPC
  carries the request's remaining deadline budget (`deadline_ctx`,
  stamped exactly like the `trace_ctx`/`obs_ctx` pair) and derives its
  socket timeout from it — `min(remaining, cap)` instead of a fixed
  per-hop 30 s; a hop arriving with an exhausted budget answers an
  immediate 408 shard failure. A failed shard RPC retries in place with
  jittered exponential backoff under a per-request retry budget, then
  FAILS OVER to the shard's next copy (`number_of_node_replicas` copies
  assigned at create_index; `MemberFailureDetector` findings demote
  suspect members in the preference order). A shard with no live copy
  left fails honestly: `_shards.failed` with per-shard reasons,
  `timed_out`/`terminated_early` response flags, and
  `allow_partial_search_results=false` converting any partiality into a
  whole-request error (reference parity). Fetch never fails over — doc
  coordinates are copy-local, so fetch sticks to the copy that ran the
  query phase (reference query-and-fetch affinity) and a copy lost
  between phases fails its shard. The seeded chaos harness
  (`cluster/faults.py`) injects drop/delay/error/blackhole at the RPC
  send/receive sites so the kill-one-node and deadline tests replay
  exact interleavings.

Unsupported on a distributed index (explicit 400, never silently wrong):
non-`_score` sorts, collapse, rescore, search_after/scroll/PIT, suggest,
profile, knn, and aggregations with sub-aggregations (their coordinator
refinement needs cross-node sub-searches; reference parity for those is
future work).
"""

from __future__ import annotations

import base64
import contextlib
import contextvars
import json
import os
import pickle
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..rest.client import ApiError, RestClient
from ..rest.http_server import HttpServer
from ..search import compiler as C
from ..search import query_dsl as dsl
from ..search.aggregations import parse_aggs
from ..search.executor import (Candidate, ShardQueryResult,
                               _global_stats_contexts, reduce_shard_results)
from ..utils import deadline as _dl
from ..utils import legs as _legs
from . import faults as _faults
from .failure import MemberFailureDetector
from .node import Node
from .routing import assign_copies, order_copies, shard_for

# transport cap, NOT the per-hop timeout: every RPC derives its actual
# socket timeout from the request's remaining deadline budget
# (min(remaining, cap)); only deadline-less requests see the full cap
_RPC_TIMEOUT_CAP_S = float(os.environ.get("OPENSEARCH_TPU_RPC_CAP_S",
                                          30.0))

# observability scrapes (cluster stats / hot_threads / history fan-out)
# get a TIGHTER default cap: a monitoring poll against a wedged member
# must degrade to a per-node `failed` entry in seconds, never hold the
# coordinator for the full transport cap. A live request deadline still
# tightens it further (deadline-ctx rides the scrape like any RPC).
_SCRAPE_CAP_S = float(os.environ.get("OPENSEARCH_TPU_SCRAPE_CAP_S", 5.0))

# Failure-detector snapshot for one top-level request.  A hybrid body
# fans its sub-retrievals out as parallel legs; each sub-search plans
# its scatter from the detector-deprioritized member set, and a plan
# taken mid-request would otherwise depend on WHEN a sibling leg's
# failure landed in the detector — a thread race.  The hybrid entry
# point snapshots the set once, and every leg (the contextvar rides
# the leg's captured context) plans against that same view, so the
# serial and parallel arms issue the same RPCs and seeded chaos
# journals stay byte-identical across arms.  Mid-request failures
# still drive retries/failover through the per-request plan state.
_fd_snap: contextvars.ContextVar[Optional[frozenset]] = \
    contextvars.ContextVar("ostpu_fd_snapshot", default=None)


class RetryPolicy:
    """Per-shard retry + failover knobs (docs/RESILIENCE.md). In-place
    retries are jittered-exponential-backoff re-sends to the SAME member
    (transient blips); the per-request `budget` bounds total retries
    across all shards so a sick cluster degrades to honest shard
    failures instead of a retry storm; `storm_n` is the request-level
    retry count that freezes a flight-recorder dump."""

    def __init__(self, same_member_retries: Optional[int] = None,
                 budget: Optional[int] = None,
                 base_backoff_s: float = 0.025,
                 backoff_mult: float = 2.0,
                 max_backoff_s: float = 0.5,
                 storm_n: Optional[int] = None):
        env = os.environ
        self.same_member_retries = int(
            same_member_retries if same_member_retries is not None
            else env.get("OPENSEARCH_TPU_RPC_RETRIES", 1))
        self.budget = int(budget if budget is not None
                          else env.get("OPENSEARCH_TPU_RETRY_BUDGET", 4))
        self.base_backoff_s = float(base_backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.max_backoff_s = float(max_backoff_s)
        # storm threshold defaults to the retry budget: a request that
        # burns its WHOLE budget is the forensic moment (a default
        # above the budget would make the dump unreachable — retries
        # are capped at the budget)
        self.storm_n = int(storm_n if storm_n is not None
                           else env.get("OPENSEARCH_TPU_RETRY_STORM_N",
                                        self.budget))


class _ShardCallFailed(Exception):
    """One member terminally failed a shard-group call (retries spent).
    `reason` is the per-shard failure record the response surfaces."""

    def __init__(self, member: str, kind: str, attempts: int):
        super().__init__(f"[{member}] {kind} after {attempts} attempt(s)")
        self.member = member
        self.kind = kind
        self.attempts = attempts


class _RequestState:
    """Per-request resilience accounting: the deadline, the shared retry
    budget, the deterministic backoff RNGs, and the flags/failure
    reasons the response assembly reads. Member legs of one request run
    CONCURRENTLY (utils/legs.py), so the retry budget is taken under a
    lock and the backoff jitter is drawn from a per-(member, leg) RNG
    seeded from the installed chaos schedule via a stable hash — thread
    interleaving can change neither a leg's jitter sequence nor a
    replay's."""

    def __init__(self, policy: RetryPolicy, dl, tl: int):
        self.policy = policy
        self.dl = dl
        self.tl = tl
        self.retries = 0
        self.failovers = 0
        self.timed_out = False
        self.storm_fired = False
        sched = _faults.installed()
        self._chaos_seed = sched.seed if sched is not None else None
        self._lock = threading.Lock()
        self._rngs: Dict[tuple, random.Random] = {}

    def rpc_timeout_s(self) -> float:
        if self.dl is None:
            return _RPC_TIMEOUT_CAP_S
        return self.dl.rpc_timeout_s(_RPC_TIMEOUT_CAP_S)

    def take_retry(self) -> bool:
        with self._lock:
            if self.retries >= self.policy.budget:
                return False
            self.retries += 1
            return True

    def _rng_for(self, member: Optional[str]) -> random.Random:
        key = (member, _legs.current_path())
        with self._lock:
            rng = self._rngs.get(key)
            if rng is None:
                if self._chaos_seed is None:
                    rng = random.Random()
                else:
                    import hashlib
                    h = hashlib.sha256(
                        f"{self._chaos_seed}|{key[0]}|{key[1]}"
                        .encode()).digest()
                    rng = random.Random(int.from_bytes(h[:8], "big"))
                self._rngs[key] = rng
            return rng

    def backoff_s(self, attempt: int,
                  member: Optional[str] = None) -> float:
        """Full-jitter exponential backoff, bounded by the cap and by
        the remaining deadline (never sleep past the budget)."""
        p = self.policy
        ceil = min(p.base_backoff_s * (p.backoff_mult ** max(attempt - 1,
                                                             0)),
                   p.max_backoff_s)
        b = self._rng_for(member).uniform(0.0, ceil)
        if self.dl is not None:
            b = min(b, max(self.dl.remaining_s(), 0.0))
        return b


# ---------------------------------------------------------------------
# statistics contexts for the cross-node DFS phase
# ---------------------------------------------------------------------

class RecordingStatsContext(C.ShardContext):
    """Wraps the local collection-stats view and records every statistic
    the query rewrite consumes — the node-local half of the DFS phase."""

    def __init__(self, mappings, segments, similarity=None,
                 field_similarities=None):
        super().__init__(mappings, segments, similarity, field_similarities)
        self.rec = {"num_docs": 0, "df": {}, "ctf": {}, "fs": {}}

    @property
    def num_docs(self) -> int:
        n = C.ShardContext.num_docs.fget(self)
        self.rec["num_docs"] = n
        return n

    def doc_freq(self, field: str, term: str) -> int:
        v = super().doc_freq(field, term)
        self.rec["df"][(field, term)] = v
        return v

    def collection_tf(self, field: str, term: str) -> float:
        v = super().collection_tf(field, term)
        self.rec["ctf"][(field, term)] = v
        return v

    def field_stats(self, field: str) -> Tuple[int, int]:
        v = super().field_stats(field)
        self.rec["fs"][field] = v
        return v


class GlobalStatsContext(C.ShardContext):
    """A stats context pinned to coordinator-summed global statistics: every
    node scores with the same idf/avgdl no matter where documents live.
    Statistics the DFS recording did not capture (rare: a fetch-side
    feature asking about a term the query rewrite never touched) fall back
    to local values — degraded, never crashing."""

    def __init__(self, mappings, segments, similarity, field_similarities,
                 g: dict):
        super().__init__(mappings, segments, similarity, field_similarities)
        self._g = g

    @property
    def num_docs(self) -> int:
        return self._g["num_docs"]

    def doc_freq(self, field: str, term: str) -> int:
        v = self._g["df"].get((field, term))
        return v if v is not None else super().doc_freq(field, term)

    def collection_tf(self, field: str, term: str) -> float:
        v = self._g["ctf"].get((field, term))
        return v if v is not None else super().collection_tf(field, term)

    def field_stats(self, field: str) -> Tuple[int, int]:
        v = self._g["fs"].get(field)
        return tuple(v) if v is not None else super().field_stats(field)


def _merge_dfs(parts: List[dict]) -> dict:
    g = {"num_docs": 0, "df": {}, "ctf": {}, "fs": {}}
    for p in parts:
        g["num_docs"] += p["num_docs"]
        for k, v in p["df"].items():
            g["df"][k] = g["df"].get(k, 0) + v
        for k, v in p["ctf"].items():
            g["ctf"][k] = g["ctf"].get(k, 0.0) + v
        for k, (dc, sdl) in p["fs"].items():
            odc, osdl = g["fs"].get(k, (0, 0))
            g["fs"][k] = (odc + dc, osdl + sdl)
    return g


# ---------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------

def _b64(obj) -> str:
    return base64.b64encode(pickle.dumps(obj)).decode("ascii")


def _unb64(s: str):
    return pickle.loads(base64.b64decode(s.encode("ascii")))


def _http(addr: str, method: str, path: str, payload=None,
          timeout: float = _RPC_TIMEOUT_CAP_S) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"}
    # shared-secret node-to-node trust: when the cluster runs with REST
    # security enabled, every /_internal call must carry this token (the
    # compact analog of the reference's transport-layer TLS mutual auth)
    tok = os.environ.get("OPENSEARCH_TPU_CLUSTER_TOKEN")
    if tok:
        headers["X-Cluster-Token"] = tok
    req = urllib.request.Request(
        f"http://{addr}{path}", data=data, method=method,
        headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        raw = r.read().decode()
    return json.loads(raw) if raw else {}


class NodeUnreachable(Exception):
    pass


# ---------------------------------------------------------------------
# the distributed node
# ---------------------------------------------------------------------

class DistClusterNode:
    """A full Node + HTTP server participating in a multi-process cluster.

    Public surface: `create_index`, `index_doc`, `refresh`, `search`,
    `get`, `cluster_state`, `stop`. Everything travels over HTTP — this
    object is also the handler for `/_internal/*` RPCs on its server.
    """

    def __init__(self, name: str, seed: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 retry_policy: Optional[RetryPolicy] = None):
        self.name = name
        self.node = Node()
        self.client = RestClient(node=self.node)
        self.server = HttpServer(self.client, host=host, port=port)
        self.server.dist = self
        self.port = self.server.start()
        self.addr = f"{host}:{self.port}"
        self._lock = threading.RLock()
        # cluster state (reference ClusterState: term/version + routing)
        self.term = 1
        self.version = 0
        self.leader = name if seed is None else None
        self.members: Dict[str, str] = {name: self.addr}
        # primary owner per shard (back-compat view of copies[...][0])
        self.routing: Dict[str, Dict[int, str]] = {}   # index -> shard -> node
        # full copy lists, primary first (index -> shard -> [members])
        self.copies: Dict[str, Dict[int, List[str]]] = {}
        self.index_bodies: Dict[str, dict] = {}
        self.retry_policy = retry_policy or RetryPolicy()
        # member-level failure detection feeding copy selection: suspect
        # members are demoted in every shard's preference order until a
        # successful probe/RPC (cluster/failure.py)
        self.member_fd = MemberFailureDetector()
        # wire the detector into an already-armed remediation actuator
        # (OPENSEARCH_TPU_REMEDIATION=1 arms at Node init, BEFORE this
        # cluster wrapper exists): without this, the deprioritize_member
        # action would be silently inert on the production arm path
        rem = self.node.remediation
        if rem is not None and rem.member_fd is None:
            rem.member_fd = self.member_fd
        # registry this node answers fleet scrapes from. None -> the
        # process-default METRICS (the one-node-per-process deployment);
        # in-process multi-node tests inject distinct registries so the
        # merge math federates genuinely disjoint streams
        self.obs_registry = None
        # insights engine this node answers `/_internal/insights` from.
        # None -> the process-default INSIGHTS; in-process multi-node
        # tests inject distinct engines so the heavy-hitter merge
        # federates genuinely disjoint workloads (the obs_registry
        # pattern above)
        self.insights_engine = None
        # remediation actuator this node's admission path consults and
        # `/_internal/remediation` answers from. None -> the
        # process-default REMEDIATOR; the traffic harness injects
        # per-node instances (same pattern as insights_engine)
        self.remediation_engine = None
        if seed is not None:
            st = _http(seed, "POST", "/_internal/join",
                       {"name": name, "addr": self.addr})
            self._apply_state(st["state"])

    # ---------------- state machine ----------------

    def _state(self) -> dict:
        # snapshot under the (reentrant) state lock, copying the member
        # and body maps: publishes json.dumps this dict OUTSIDE the lock
        # (OSL702 fan-out), so handing out live references let a
        # concurrent join blow up the serializer ("dict changed size
        # during iteration") or ship different member sets per target
        with self._lock:
            return {"term": self.term, "version": self.version,
                    "leader": self.leader, "members": dict(self.members),
                    "routing": {i: {str(s): n for s, n in r.items()}
                                for i, r in self.routing.items()},
                    "copies": {i: {str(s): list(c) for s, c in r.items()}
                               for i, r in self.copies.items()},
                    "index_bodies": dict(self.index_bodies)}

    def _apply_state(self, st: dict) -> None:
        with self._lock:
            # Publish fan-outs run unserialized (outside the state
            # lock), so a slow send can deliver version N after a fast
            # one delivered N+1; applying it would regress to stale
            # state and silently drop the newer member/index. Ignore
            # anything not strictly newer (a higher term always wins).
            if (st["term"], st["version"]) <= (self.term, self.version):
                return
            self.term = st["term"]
            self.version = st["version"]
            self.leader = st["leader"]
            self.members = dict(st["members"])
            self.routing = {i: {int(s): n for s, n in r.items()}
                            for i, r in st["routing"].items()}
            # pre-copies states (rolling upgrade shape): primaries only
            self.copies = {i: {int(s): list(c) for s, c in r.items()}
                           for i, r in st.get("copies", {}).items()}
            for i, r in self.routing.items():
                self.copies.setdefault(i, {s: [n] for s, n in r.items()})
            self.index_bodies = dict(st["index_bodies"])
            # idempotently materialize any index this node doesn't have yet
            for iname, body in self.index_bodies.items():
                if iname not in self.node.indices:
                    self.client.indices.create(iname, body)

    def _publish(self) -> None:
        """Leader: bump version, push full state to every member (self
        applies synchronously). Unreachable members keep their shards in
        the routing table; searches report them failed until they rejoin."""
        # bump + snapshot under the (reentrant) state lock: the unlocked
        # bump raced `_apply_state`'s locked `self.version = st["version"]`
        with self._lock:
            self.version += 1
            st = self._state()
        from ..utils.metrics import METRICS
        for name, addr in list(self.members.items()):
            if name == self.name:
                continue
            try:
                _http(addr, "POST", "/_internal/publish", {"state": st})
            except (urllib.error.URLError, OSError):
                # best-effort publish by design — but never silently:
                # the member keeps its shards in routing and searches
                # report them failed until it rejoins (OSL508)
                METRICS.counter("dist.publish.failed").inc()

    # ---------------- internal RPC handler (called by HttpServer) --------

    def handle_internal(self, method: str, parts: List[str], body: dict
                        ) -> Tuple[int, dict]:
        op = parts[1] if len(parts) > 1 else ""
        if _faults.enabled():
            # serving-side chaos site: a rule here makes THIS node the
            # slow/flaky one (cluster/faults.py)
            _faults.on_rpc_recv(self.name, op)
        if op == "ping" and method == "GET":
            # failure-detector probe target (cluster/failure.py)
            return 200, {"ok": True, "node": self.name}
        if op == "join" and method == "POST":
            # record the member under the lock, but fan the publish out
            # AFTER releasing it: _publish RPCs every member, and holding
            # the state lock across those sends serialized every other
            # join/search-route against the slowest member (OSL702)
            with self._lock:
                self.members[body["name"]] = body["addr"]
            self._publish()
            with self._lock:
                return 200, {"state": self._state()}
        if op == "publish" and method == "POST":
            self._apply_state(body["state"])
            return 200, {"acknowledged": True}
        if op in ("dfs", "query_phase", "fetch_phase",
                  "stats", "node_stats", "hot_threads", "history",
                  "insights", "remediation", "indexing"):
            # deadline propagation: re-anchor the remaining budget the
            # coordinator stamped; an already-exhausted budget answers an
            # immediate 408 shard failure instead of a full local phase
            # (observability scrapes ride the same contract — a fleet
            # poll under a request deadline degrades honestly)
            dl = _dl.Deadline.from_wire(body.get("deadline_ctx"))
            if dl is not None and dl.exhausted():
                from ..utils.metrics import METRICS
                METRICS.counter("dist.deadline.expired_on_arrival").inc()
                return 408, {"error": {
                    "type": "request_timeout_exception",
                    "reason": f"[{op}] arrived with an exhausted "
                              f"deadline budget"}}
            with _dl.scope(dl):
                if op in ("stats", "node_stats", "hot_threads",
                          "history", "insights", "remediation",
                          "indexing"):
                    return 200, self._handle_obs(op, body)
                return self._handle_phase(op, body)
        if op == "state" and method == "GET":
            return 200, {"state": self._state()}
        if op == "create_index" and method == "POST":
            return 200, self.create_index(parts[2], body)
        if op == "search" and method == "POST":
            # run a DISTRIBUTED search coordinated by THIS node (any member
            # can coordinate, like any reference node with the coordinator
            # role); the origin lane rides the payload so remediation
            # admission and per-lane SLIs hold on this path too
            return 200, self.search(body["index"], body["body"],
                                    lane=body.get("lane", "interactive"))
        return 404, {"error": {"type": "resource_not_found_exception",
                               "reason": f"unknown internal op [{op}]"}}

    def _handle_phase(self, op: str, body: dict) -> Tuple[int, dict]:
        shards = ([int(s) for s in body["shards"]]
                  if body.get("shards") is not None else None)
        if op == "dfs":
            with self._rpc_span("dist.dfs", body) as s, \
                    self._rpc_timeline("dfs", body) as rtl:
                recs = self._local_dfs(body["index"], body["body"],
                                       shards)
            return 200, {"recs": _b64(recs), "span": self._span_out(s),
                         "obs": self._obs_out(rtl)}
        if op == "query_phase":
            with self._rpc_span("dist.query_phase", body) as s, \
                    self._rpc_timeline("query_phase", body) as rtl:
                results = self._local_query(body["index"], body["body"],
                                            _unb64(body["g"]), shards)
            return 200, {"results": _b64(results),
                         "span": self._span_out(s),
                         "obs": self._obs_out(rtl)}
        with self._rpc_span("dist.fetch_phase", body) as s, \
                self._rpc_timeline("fetch_phase", body) as rtl:
            hits = self._local_fetch(body["index"], body["body"],
                                     int(body["shard"]),
                                     _unb64(body["cands"]),
                                     _unb64(body["g"]))
        return 200, {"hits": _b64(hits), "span": self._span_out(s),
                     "obs": self._obs_out(rtl)}

    # ---------------- trace propagation over the wire ----------------
    #
    # The coordinator stamps every /_internal RPC payload with its trace
    # context (`trace_ctx`); the serving node runs the local phase under a
    # span carrying that context and RETURNS the finished span tree in
    # the response, which the coordinator grafts under its own phase span
    # (`TRACER.attach_remote`) — so one distributed search reads as ONE
    # coherent parent-child trace on the coordinating node, while each
    # member's ring still holds its local half, attributable via the
    # stamped parent ids.

    def _rpc_span(self, name: str, body: dict):
        from ..utils.trace import TRACER
        tctx = body.get("trace_ctx") or {}
        return TRACER.span(name, node=self.name,
                           **{k: tctx[k] for k in
                              ("trace_root_id", "parent_span_id",
                               "coordinator") if k in tctx})

    @staticmethod
    def _span_out(s) -> Optional[dict]:
        return s.to_dict() if s is not None else None

    # ---------------- flight-recorder stitching over the wire ---------
    #
    # Mirrors the trace propagation above: the coordinator stamps its
    # (node, timeline) onto every RPC; the serving node runs the local
    # phase under its OWN timeline carrying the origin linkage, and the
    # response returns that timeline's events, which the coordinator
    # grafts into the request's journal (`RECORDER.graft`) — so one
    # distributed search reads as ONE stitched cross-node timeline.

    @contextlib.contextmanager
    def _rpc_timeline(self, op: str, body: dict):
        from ..obs import flight_recorder as _fr
        ctx = body.get("obs_ctx")
        if not _fr.RECORDER.enabled or not isinstance(ctx, dict):
            yield 0
            return
        tl = _fr.RECORDER.start(f"rpc.{op}", node=self.name,
                                origin_node=ctx.get("node"),
                                origin_timeline=ctx.get("timeline"))
        token = _fr.set_current(tl)
        try:
            if tl:
                _fr.RECORDER.record(tl, "rpc.accept", op=op,
                                    node=self.name)
            yield tl
        finally:
            _fr.reset_current(token)

    @staticmethod
    def _obs_out(tl: int) -> Optional[list]:
        if not tl:
            return None
        from ..obs import flight_recorder as _fr
        return _fr.RECORDER.timeline_events(tl)

    def _rpc(self, member: str, op: str, payload: dict,
             timeout_s: Optional[float] = None,
             dl: Optional[_dl.Deadline] = None) -> dict:
        """Coordinator-side RPC with trace stamping + span grafting +
        flight-recorder timeline stitching + deadline propagation +
        latency accounting. The socket timeout is deadline-derived
        (min(remaining, cap)); the remaining budget rides the payload as
        `deadline_ctx` exactly like `trace_ctx`/`obs_ctx` do."""
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        from ..utils.trace import TRACER
        if dl is None:
            dl = _dl.current()
        if timeout_s is None:
            timeout_s = (dl.rpc_timeout_s(_RPC_TIMEOUT_CAP_S)
                         if dl is not None else _RPC_TIMEOUT_CAP_S)
        wctx = TRACER.wire_context()
        if wctx is not None:
            payload = dict(payload,
                           trace_ctx=dict(wctx, coordinator=self.name))
        tl = _fr.current() if _fr.RECORDER.enabled else 0
        if tl:
            payload = dict(payload,
                           obs_ctx={"node": self.name, "timeline": tl})
        if dl is not None:
            # stamped at send time: the receiving hop re-anchors what is
            # left, so queue/transit time is charged to the budget
            payload = dict(payload, deadline_ctx=dl.to_wire())
        t0 = time.monotonic()
        try:
            if _faults.enabled():
                # inside the try: injected faults go through the SAME
                # failure accounting (metrics, detector, events) as real
                # ones — the harness must not produce divergent journals
                _faults.on_rpc_send(member, op, timeout_s)
            r = _http(self.members[member], "POST", f"/_internal/{op}",
                      payload, timeout=timeout_s)
        except urllib.error.HTTPError as e:
            if e.code < 500:
                # the member ANSWERED (408 deadline refusal, 4xx API
                # error): that is member health, not member death — no
                # detector demotion, no transport-failure count
                raise
            METRICS.counter("dist.rpc.failed").inc()
            self.member_fd.note_failure(member)
            if tl:
                _fr.RECORDER.record(tl, "rpc.failed", op=op, node=member)
            raise
        except Exception:
            METRICS.counter("dist.rpc.failed").inc()
            self.member_fd.note_failure(member)
            if tl:
                _fr.RECORDER.record(tl, "rpc.failed", op=op, node=member)
            raise
        self.member_fd.note_success(member)
        METRICS.histogram(f"dist.rpc.{op}").record(
            (time.monotonic() - t0) * 1000.0)
        TRACER.attach_remote(r.get("span"))
        _fr.RECORDER.graft(tl, r.get("obs"), node=member)
        return r

    def _rpc_failsafe(self, member: str, op: str, payload: dict,
                      rs: _RequestState) -> dict:
        """`_rpc` under the retry policy: in-place re-sends with jittered
        exponential backoff for transient failures, bounded by the
        per-request retry budget and the deadline. Terminal outcomes:

        - `DeadlineExhausted` — the budget ran out (locally, or the
          remote answered 408); never retried, the shard fails with a
          timeout reason and the response gets `timed_out: true`.
        - `_ShardCallFailed` — retries spent; the caller fails the
          shard over to its next copy (`rpc.failover`) or surfaces it.
        - Any non-5xx HTTPError — a genuine API error (e.g. 400),
          re-raised untouched.
        """
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        attempts = 0
        while True:
            if rs.dl is not None and rs.dl.exhausted():
                rs.timed_out = True
                METRICS.counter("dist.deadline.exhausted").inc()
                if rs.tl:
                    _fr.RECORDER.record(rs.tl, "deadline.exhausted",
                                        op=op, node=member)
                raise _dl.DeadlineExhausted(
                    f"[{op}] to [{member}]: request budget exhausted")
            try:
                return self._rpc(member, op, payload,
                                 timeout_s=rs.rpc_timeout_s(), dl=rs.dl)
            except urllib.error.HTTPError as e:
                if e.code == 408:
                    # the hop measured the budget exhausted — retrying
                    # cannot help inside the same budget
                    rs.timed_out = True
                    METRICS.counter("dist.deadline.exhausted").inc()
                    if rs.tl:
                        _fr.RECORDER.record(rs.tl, "deadline.exhausted",
                                            op=op, node=member)
                    raise _dl.DeadlineExhausted(
                        f"[{member}] rejected [{op}]: budget exhausted")
                if e.code < 500:
                    raise
                kind = "internal_error"
            except (urllib.error.URLError, TimeoutError, OSError):
                kind = "node_unreachable"
            attempts += 1
            if attempts > rs.policy.same_member_retries \
                    or not rs.take_retry():
                raise _ShardCallFailed(member, kind, attempts)
            backoff = rs.backoff_s(attempts, member=member)
            METRICS.counter("dist.rpc.retry").inc()
            METRICS.histogram("dist.rpc.backoff_ms").record(
                backoff * 1000.0)
            if rs.tl:
                _fr.RECORDER.record(rs.tl, "rpc.retry", op=op,
                                    node=member, attempt=attempts,
                                    backoff_ms=round(backoff * 1000.0, 3))
            if not rs.storm_fired and rs.retries >= rs.policy.storm_n:
                # retry storm: the forensic moment — freeze the journal
                # before the request degrades further
                rs.storm_fired = True
                if _fr.RECORDER.enabled and rs.tl:
                    _fr.RECORDER.trigger(
                        "retry_storm", [rs.tl],
                        note=f"{rs.retries} retries in one request "
                             f"(storm_n={rs.policy.storm_n})")
            if backoff > 0:
                time.sleep(backoff)

    # ---------------- cluster API ----------------

    def cluster_state(self) -> dict:
        return self._state()

    @staticmethod
    def _node_replicas(body: dict) -> int:
        """`index.number_of_node_replicas` — CROSS-NODE shard copies
        (distinct from `number_of_replicas`, which allocates intra-node
        device copies). Default 0: primaries-only, the pre-resilience
        layout."""
        settings = (body or {}).get("settings", {}) or {}
        v = settings.get("index", {}).get(
            "number_of_node_replicas",
            settings.get("number_of_node_replicas", 0))
        return max(int(v), 0)

    def create_index(self, name: str, body: dict) -> dict:
        """Leader-only (forwarded if called on a follower): create on
        every member, assign each shard an ordered COPY list (primary
        first, `number_of_node_replicas` extra members) round-robin over
        sorted member names."""
        if self.leader != self.name:
            return _http(self.members[self.leader], "POST",
                         f"/_internal/create_index/{name}", body)
        # mutate routing state under the lock, then fan the member PUTs
        # and the publish out AFTER releasing it: a slow/dead member
        # otherwise blocks every search-route and join for the full HTTP
        # timeout while we hold the state lock (OSL702). The snapshots
        # taken under the lock keep the returned routing/copies coherent
        # even if a concurrent create lands between release and return.
        with self._lock:
            self.client.indices.create(name, body)
            n_shards = self.node.indices[name].meta.num_shards
            copies = assign_copies(
                n_shards, self.members, 1 + self._node_replicas(body))
            routing = {s: c[0] for s, c in copies.items()}
            self.copies[name] = copies
            self.routing[name] = routing
            self.index_bodies[name] = body
            targets = [(m, a) for m, a in self.members.items()
                       if m != self.name]
        for _mname, addr in targets:
            _http(addr, "PUT", f"/{name}", body)
        self._publish()
        return {"acknowledged": True, "index": name,
                "routing": routing, "copies": copies}

    def index_doc(self, index: str, doc: dict, id: str,
                  refresh: bool = False) -> dict:
        """Route by doc id; write through EVERY copy holder of the doc's
        shard (primary first) over the public doc endpoint — copies stay
        byte-identical when writers are externally ordered (one
        coordinator per doc id, the bulk-load shape): every holder then
        applies the same doc stream in the same order. CONCURRENT
        same-id writes through different coordinators can interleave
        differently per holder (no primary sequencing yet — reference
        primary-term ordering is future work). A primary failure fails
        the write with
        nothing applied; a REPLICA failure after the primary applied is
        surfaced as a 500 naming the diverged copy (counted in
        `dist.replica_write_failed`) — the caller must retry or drop the
        copy; silent divergence would poison failover byte-identity
        (stale-copy repair is future work)."""
        import time as _t

        from ..obs import ingest_obs as _iobs
        from ..utils.metrics import METRICS
        r = self.routing.get(index)
        if r is None:
            raise ApiError(404, "index_not_found_exception",
                           f"no such index [{index}]")
        n = self.node.indices[index].meta.num_shards
        shard = shard_for(id, n)
        holders = self.copies.get(index, {}).get(shard, [r[shard]])
        refresh_q = "?refresh=true" if refresh else ""
        t0 = _t.perf_counter()
        out = None
        for ord_, holder in enumerate(holders):
            try:
                if holder == self.name:
                    res = self.client.index(index, doc, id=id,
                                            refresh=refresh)
                else:
                    res = _http(self.members[holder], "PUT",
                                f"/{index}/_doc/{id}{refresh_q}", doc)
            except (urllib.error.URLError, OSError) as e:
                if ord_ == 0:
                    raise   # primary never applied: clean failure
                METRICS.counter("dist.replica_write_failed").inc()
                _iobs.count("indexing.replica.failed")
                raise ApiError(
                    500, "replica_write_exception",
                    f"doc [{id}] applied on {holders[:ord_]} but copy "
                    f"[{holder}] failed ({type(e).__name__}): copies "
                    f"have diverged — retry the write or remove the "
                    f"copy")
            if out is None:
                out = res
        if len(holders) > 1 and _iobs.enabled():
            # whole-fanout wall time (primary + every copy), the
            # write-through analog of the replica sync span
            METRICS.counter("indexing.replica.write_through").inc(
                len(holders) - 1)
            METRICS.histogram("indexing.replica.fanout_ms").record(
                (_t.perf_counter() - t0) * 1000.0)
        return out

    def get(self, index: str, id: str) -> dict:
        owner = self._owner(index, id)
        if owner == self.name:
            return self.client.get(index, id)
        try:
            return _http(self.members[owner], "GET", f"/{index}/_doc/{id}")
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, "resource_not_found_exception",
                           f"[{id}] not found")

    def refresh(self, index: str) -> None:
        from ..utils.metrics import METRICS
        self.client.indices.refresh(index)
        for mname, addr in self.members.items():
            if mname == self.name:
                continue
            try:
                _http(addr, "POST", f"/{index}/_refresh")
            except (urllib.error.URLError, OSError):
                # an unreachable member misses the refresh; its copies
                # serve stale until it rejoins — counted, never silent
                # (OSL508). Mirrored into the write-path failure family
                # so the ingest observatory sees it too.
                METRICS.counter("dist.refresh.failed").inc()
                from ..obs import ingest_obs as _iobs
                _iobs.count("indexing.refresh.fanout_failed")

    def _owner(self, index: str, id: str) -> str:
        r = self.routing.get(index)
        if r is None:
            raise ApiError(404, "index_not_found_exception",
                           f"no such index [{index}]")
        n = self.node.indices[index].meta.num_shards
        return r[shard_for(id, n)]

    # ---------------- distributed search ----------------

    # knn left this list with the hybrid-retrieval subsystem (PR 15):
    # the per-shard knn program needs no cross-shard state beyond the
    # DFS stats that already ride every scatter, so both the ES-style
    # top-level `knn` section and `query.knn` serve distributed now
    _UNSUPPORTED = ("collapse", "rescore", "search_after", "suggest",
                    "profile", "scroll", "pit")

    def _check_supported(self, body: dict) -> List:
        for k in self._UNSUPPORTED:
            if body.get(k):
                raise ApiError(400, "illegal_argument_exception",
                               f"[{k}] is not supported on a distributed "
                               f"index")
        for s in body.get("sort", []):
            f = s if isinstance(s, str) else next(iter(s))
            if f != "_score":
                raise ApiError(400, "illegal_argument_exception",
                               "only _score sort is supported on a "
                               "distributed index")
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        for an in (agg_nodes or []):
            if an.subs:
                raise ApiError(400, "illegal_argument_exception",
                               "sub-aggregations are not supported on a "
                               "distributed index")
        return agg_nodes or []

    def _local_dfs(self, index: str, body: dict,
                   shards: Optional[List[int]] = None) -> Dict[int, dict]:
        """Per-SHARD collection statistics (the coordinator sums exactly
        one copy of every shard, so replicated copies never double-count
        df/avgdl). `shards=None` covers every local shard — a
        convenience for direct callers/tests; the search path always
        sends an explicit plan."""
        svc = self.node.indices[index]
        searchers = svc.searchers
        if shards is None:
            shards = list(range(len(searchers)))
        out: Dict[int, dict] = {}
        for sid in shards:
            segs = list(searchers[sid].engine.segments)
            ctx = RecordingStatsContext(
                svc.mappings, segs, svc.default_sim,
                getattr(svc, "field_similarities", None))
            try:
                from ..search.executor import _collect_named
                lroot = C.rewrite(dsl.parse_query(body.get("query")), ctx,
                                  scoring=True)
                # named queries are fetch-side state that does not cross
                # the wire yet; piggyback the check on the rewrite DFS
                # already does
                ctx.rec["named"] = bool(_collect_named(lroot))
            except dsl.QueryParseError:
                pass
            _ = ctx.num_docs      # maxDoc is always part of the DFS result
            # avgdl (per-field doc_count + sum_dl) is consumed at the
            # prepare stage, not rewrite — record it for every text field
            # this shard holds so the merged fs covers whatever the query
            # touches
            for s in segs:
                for f in s.text_stats:
                    ctx.field_stats(f)
            out[sid] = ctx.rec
        return out

    def _global_ctx(self, index: str, g: dict) -> GlobalStatsContext:
        svc = self.node.indices[index]
        segs = [s for sr in svc.searchers for s in sr.engine.segments]
        return GlobalStatsContext(svc.mappings, segs, svc.default_sim,
                                  getattr(svc, "field_similarities", None),
                                  g)

    def _local_query(self, index: str, body: dict, g: dict,
                     shards: Optional[List[int]] = None
                     ) -> List[ShardQueryResult]:
        """Query phase for the REQUESTED shards (the coordinator's plan
        assigns each shard to exactly one live copy holder) with global
        stats; results stripped of segment references (they do not cross
        the wire). `shards=None` runs every local shard — direct
        callers/tests only; the search path always sends a plan."""
        svc = self.node.indices[index]
        ctx = self._global_ctx(index, g)
        if shards is None:
            shards = list(range(len(svc.searchers)))
        out = []
        for i in shards:
            r = svc.searchers[i].query_phase(dict(body), shard_ord=i,
                                             stats_ctx=ctx)
            r.segments = []        # host-local only
            r.named_by_doc = {}
            out.append(r)
        return out

    def _local_fetch(self, index: str, body: dict, shard: int,
                     cands: List[tuple], g: dict) -> List[dict]:
        svc = self.node.indices[index]
        s = svc.searchers[shard]
        segs = (list(s.replica.segments) if s.replica is not None
                else list(s.engine.segments))
        result = ShardQueryResult(shard=shard, segments=segs)
        sel = [Candidate(shard, so, ld, sc, tuple(sv), tuple(rv))
               for so, ld, sc, sv, rv in cands]
        return s.fetch_phase(result, sel, dict(body),
                             stats_ctx=self._global_ctx(index, g))

    def search(self, index: str, body: dict,
               lane: str = "interactive") -> dict:
        """Distributed DFS_QUERY_THEN_FETCH across every member, reduced
        once on this node. The whole scatter/gather runs under ONE root
        span; every remote leg's span tree comes back on the RPC response
        and nests under the coordinator's phase span. Same deal for the
        flight recorder: the coordinator owns one timeline, every RPC
        carries it, and the remote legs' events graft back into it.
        A `timeout` in the body becomes the request deadline: every RPC
        and every local segment loop downstream derives its budget from
        it (utils/deadline.py). `lane` is the workload lane the SLIs and
        the remediation admission match run under (the wlm lane the REST
        facade derives on the single-node path)."""
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        from ..utils.trace import TRACER
        from ..utils.wlm import PressureRejectedException
        try:
            dl = (_dl.current() or _dl.Deadline.from_body(body))
        except ValueError as e:
            raise ApiError(400, "parsing_exception", str(e))
        # remediation admission at the COORDINATOR boundary
        # (serving/remediator.py): an alert-named shape on the batch
        # lane sheds with 429 + Retry-After. A matching interactive
        # request is counted as deprioritized, but SLIs and insights
        # keep the ORIGIN lane — the distributed path has no scheduler
        # lanes to demote into, and relabeling would hide the burn
        # from the SLO that fired it. Inert while no action engaged.
        try:
            self._remediation().admit(body, lane)
        except PressureRejectedException as e:
            self._insights().record_rejection(
                body if isinstance(body, dict) else {}, lane,
                source="remediation")
            from ..rest.client import _rejected_429
            raise _rejected_429(e)
        token = None
        if _fr.RECORDER.enabled and not _fr.current():
            tl = _fr.RECORDER.start("dist.search", index=index,
                                    node=self.name)
            token = _fr.set_current(tl)
        # per-lane SLIs at the COORDINATOR boundary (the distributed
        # path never crosses Node.search): the same requests/errors
        # counters + latency sketch the SLO engine windows (obs/slo.py),
        # and the same query-insights fingerprinting — distributed
        # workloads aggregate under the identical shape identity a
        # single node derives (obs/insights.py)
        from ..obs import insights as _ins
        t0 = time.monotonic()
        obs, ins_token = _ins.begin(body if isinstance(body, dict)
                                    else {}, lane)
        ins_tl = _fr.current() if _fr.RECORDER.enabled else 0
        try:
            with _dl.scope(dl), \
                    TRACER.span("dist.search", index=index,
                                coordinator=self.name):
                if _fr.RECORDER.enabled and _fr.current():
                    _fr.RECORDER.record(_fr.current(), "dist.accept",
                                        index=index,
                                        coordinator=self.name)
                resp = self._search_traced(index, body)
        except BaseException as e:
            # client-side 4xx API errors are the caller's fault, not
            # lost availability (the Node.search contract)
            is_5xx = getattr(e, "status", 500) >= 500
            if is_5xx:
                METRICS.counter(f"search.lane.{lane}.errors").inc()
            _ins.finish(ins_token, obs, error=is_5xx,
                        timeline_id=ins_tl)
            raise
        finally:
            if token is not None:
                _fr.reset_current(token)
        METRICS.counter(f"search.lane.{lane}.requests").inc()
        took_ms = (time.monotonic() - t0) * 1000.0
        if METRICS.enabled:
            METRICS.histogram(f"search.lane.{lane}.latency_ms").record(
                took_ms)
        _ins.finish(ins_token, obs, latency_ms=took_ms,
                    timeline_id=ins_tl)
        return resp

    # ---------------- per-phase scatter with retry + failover ----------

    def _scatter_phase(self, op: str, plan: Dict[int, List[str]],
                       shards: List[int], rs: _RequestState,
                       failures: Dict[int, dict], run_local,
                       run_remote) -> Tuple[Dict[int, object],
                                            Dict[int, str]]:
        """Run one phase over `shards`: group by each shard's preferred
        live copy, fan every member group of the round out as one
        parallel leg (`utils/legs.py` — self-legs run locally, the rest
        RPC), JOIN, and on a member's terminal failure FAIL each of its
        shards OVER to the next copy in `plan` (mutated in place so
        later phases inherit the discovered topology). A shard with no
        copies left lands in `failures` with its per-shard reason.
        Round latency is the MAX of the member legs, not the SUM; the
        failover re-planning between rounds runs on THIS thread in
        sorted member order, so plan mutation and failure bookkeeping
        stay exactly as deterministic as the serial loop
        (`OPENSEARCH_TPU_LEGS=0`). Returns (per-shard outputs,
        per-shard serving member)."""
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        outputs: Dict[int, object] = {}
        assigned: Dict[int, str] = {}
        pending = [s for s in shards if s not in failures]
        while pending:
            groups: Dict[str, List[int]] = {}
            for s in pending:
                groups.setdefault(plan[s][0], []).append(s)
            next_pending: List[int] = []
            members = sorted(groups)
            ls = _legs.LegSet(f"dist.{op}")
            for member in members:
                mshards = sorted(groups[member])

                def leg(member=member, mshards=mshards):
                    if rs.dl is not None and rs.dl.exhausted():
                        raise _dl.DeadlineExhausted(
                            f"[{op}] budget exhausted")
                    if member == self.name:
                        return run_local(mshards)
                    return run_remote(member, mshards)
                ls.add_leg(leg, name=member)
            deadline_hit = False
            for member, leg_out in zip(members, ls.join()):
                mshards = sorted(groups[member])
                err = leg_out.error
                if err is None:
                    res = leg_out.value
                    for s in mshards:
                        outputs[s] = res[s]
                        assigned[s] = member
                elif isinstance(err, (_dl.DeadlineExhausted,
                                      _legs.LegWedged)):
                    # terminal for the whole phase: this leg's shards
                    # fail with a timeout reason — within budget, never
                    # a transport-cap stall. Sibling legs that DID
                    # complete keep their results (the serial arm would
                    # simply never have attempted them), and no further
                    # failover round starts (below).
                    rs.timed_out = True
                    deadline_hit = True
                    for s in mshards:
                        failures.setdefault(s, {
                            "type": "timeout_exception",
                            "node": plan[s][0] if plan[s] else None,
                            "reason": "request budget exhausted"})
                elif isinstance(err, _ShardCallFailed):
                    for s in mshards:
                        plan[s] = [m for m in plan[s] if m != err.member]
                        if plan[s]:
                            rs.failovers += 1
                            METRICS.counter("dist.rpc.failover").inc()
                            if rs.tl:
                                _fr.RECORDER.record(
                                    rs.tl, "rpc.failover", op=op,
                                    shard=s, from_node=err.member,
                                    to_node=plan[s][0])
                            next_pending.append(s)
                        else:
                            METRICS.counter("dist.shard_failed").inc()
                            failures[s] = {"type": err.kind,
                                           "node": err.member,
                                           "attempts": err.attempts}
                else:
                    # genuine API/coordinator errors propagate exactly
                    # as they did from the serial loop (first in member
                    # order)
                    raise err
            if deadline_hit or (next_pending and rs.dl is not None
                                and rs.dl.exhausted()):
                rs.timed_out = True
                for s in next_pending:
                    failures.setdefault(s, {
                        "type": "timeout_exception",
                        "node": plan[s][0] if plan[s] else None,
                        "reason": "request budget exhausted"})
                return outputs, assigned
            pending = next_pending
        return outputs, assigned

    def _remote_runner(self, op: str, rs: _RequestState, build_payload,
                       extract):
        """Wrap an RPC phase leg: `_rpc_failsafe` for the wire, and a
        malformed/incomplete response converts to a member failure (the
        old `KeyError` handling) instead of a coordinator crash."""

        def run(member: str, shards: List[int]):
            r = self._rpc_failsafe(member, op, build_payload(shards), rs)
            try:
                out = extract(r, shards)
                if any(s not in out for s in shards):
                    raise KeyError("incomplete phase response")
            except Exception:
                self.member_fd.note_failure(member)
                raise _ShardCallFailed(member, "bad_response", 1)
            return out
        return run

    def _search_traced(self, index: str, body: dict) -> dict:
        from ..obs import flight_recorder as _fr
        from ..utils.metrics import METRICS
        from ..utils.trace import TRACER
        from ..search import fusion
        if fusion.is_hybrid_body(body):
            # hybrid retrieval at the DISTRIBUTED coordinator: each
            # sub-query runs the full DFS→scatter→reduce→fetch ladder
            # (replica failover, deadline propagation and all) and the
            # fused page is the same pure function of the ranked
            # sub-pages the single-node arm computes — byte-identical
            # across arms by construction (search/fusion.py)
            try:
                hq = fusion.parse_hybrid(body)
            except dsl.QueryParseError as e:
                raise ApiError(400, "parsing_exception", str(e))
            tok = _fd_snap.set(frozenset(self.member_fd.deprioritized()))
            try:
                return fusion.run_hybrid(
                    body, lambda sub: self._search_traced(index, sub),
                    q=hq)
            finally:
                _fd_snap.reset(tok)
        t0 = time.monotonic()
        agg_nodes = self._check_supported(body)
        svc = self.node.indices.get(index)
        if svc is None:
            raise ApiError(404, "index_not_found_exception",
                           f"no such index [{index}]")
        n_shards = svc.meta.num_shards
        copies = self.copies.get(
            index, {s: [self.name] for s in range(n_shards)})
        # per-request copy preference: configured order with
        # detector-deprioritized members demoted; the scatter phases
        # mutate the plan as they discover dead copies, so later phases
        # inherit the topology the earlier ones learned.  Inside a
        # hybrid fan-out, every sub-retrieval plans from the snapshot
        # taken at the hybrid entry (see _fd_snap) rather than a
        # mid-request read that would race with sibling legs.
        snap = _fd_snap.get()
        depri = set(snap) if snap is not None \
            else self.member_fd.deprioritized()
        plan = {s: order_copies(copies.get(s, [self.name]), depri)
                for s in range(n_shards)}
        rs = _RequestState(self.retry_policy, _dl.current(),
                           _fr.current() if _fr.RECORDER.enabled else 0)
        failures: Dict[int, dict] = {}
        all_shards = list(range(n_shards))

        # --- phase 1: DFS (one copy of every shard's collection stats)
        with TRACER.span("dist.dfs", shards=n_shards), \
                METRICS.timer("dist.dfs"):
            dfs_out, _dfs_assigned = self._scatter_phase(
                "dfs", plan, all_shards, rs, failures,
                run_local=lambda sh: self._local_dfs(index, body, sh),
                run_remote=self._remote_runner(
                    "dfs", rs,
                    lambda sh: {"index": index, "body": body,
                                "shards": sh},
                    lambda r, sh: {s: rec for s, rec in
                                   _unb64(r["recs"]).items()
                                   if s in set(sh)}))
        if any(rec.get("named") for rec in dfs_out.values()):
            raise ApiError(400, "illegal_argument_exception",
                           "named queries (_name) are not supported "
                           "on a distributed index")
        g = _merge_dfs([dfs_out[s] for s in sorted(dfs_out)])

        # --- phase 2: QUERY the same copies with pinned global stats
        with TRACER.span("dist.query", shards=len(dfs_out)), \
                METRICS.timer("dist.query"):
            q_out, q_assigned = self._scatter_phase(
                "query_phase", plan, sorted(dfs_out), rs, failures,
                run_local=lambda sh: {
                    r.shard: r
                    for r in self._local_query(index, body, g, sh)},
                run_remote=self._remote_runner(
                    "query_phase", rs,
                    lambda sh: {"index": index, "body": body,
                                "g": _b64(g), "shards": sh},
                    lambda r, sh: {sr.shard: sr
                                   for sr in _unb64(r["results"])
                                   if sr.shard in sh}))
        merged = [q_out[s] for s in sorted(q_out)]

        with TRACER.span("dist.reduce", shards=len(merged)):
            reduced = reduce_shard_results(merged, body,
                                           agg_nodes=agg_nodes)

        # --- phase 3: FETCH winners from the copy that ran their query
        # phase (doc coordinates are copy-local: fetch retries in place
        # but never fails over — a copy lost between phases fails its
        # shard honestly, reference query-and-fetch affinity)
        by_shard: Dict[int, List[Candidate]] = {}
        for c in reduced["selected"]:
            by_shard.setdefault(c.shard, []).append(c)
        hits_by_key: Dict[Tuple, dict] = {}
        with TRACER.span("dist.fetch", shards=len(by_shard)), \
                METRICS.timer("dist.fetch"):
            # one leg per shard (fetch has no failover — retries in
            # place, copy affinity): legs overlap the per-copy fetch
            # RPCs, the per-shard failure bookkeeping below runs on
            # this thread in shard order
            fetch_items = sorted(by_shard.items())
            fls = _legs.LegSet("dist.fetch")
            for s_id, sel in fetch_items:
                owner = q_assigned.get(s_id, self.name)

                def fleg(s_id=s_id, sel=sel, owner=owner):
                    if owner == self.name:
                        sr = self.node.indices[index].searchers[s_id]
                        segs = (list(sr.replica.segments)
                                if sr.replica is not None
                                else list(sr.engine.segments))
                        res = ShardQueryResult(shard=s_id, segments=segs)
                        return sr.fetch_phase(
                            res, sel, dict(body),
                            stats_ctx=self._global_ctx(index, g))
                    cands = [(c.seg_ord, c.local_doc, c.score,
                              list(c.sort_values),
                              list(c.raw_sort_values))
                             for c in sel]
                    r = self._rpc_failsafe(
                        owner, "fetch_phase",
                        {"index": index, "body": body,
                         "shard": s_id, "cands": _b64(cands),
                         "g": _b64(g)}, rs)
                    return _unb64(r["hits"])
                fls.add_leg(fleg, name=str(s_id))
            for (s_id, sel), leg_out in zip(fetch_items, fls.join()):
                owner = q_assigned.get(s_id, self.name)
                err = leg_out.error
                remote = owner != self.name
                if err is None:
                    fetched = leg_out.value
                elif remote and isinstance(err, (_dl.DeadlineExhausted,
                                                 _legs.LegWedged)):
                    rs.timed_out = True
                    failures[s_id] = {
                        "type": "timeout_exception", "node": owner,
                        "reason": "request budget exhausted"}
                    fetched = []
                elif remote and isinstance(err,
                                           (_ShardCallFailed, KeyError)):
                    # the copy died BETWEEN query and fetch: this
                    # shard's winners can no longer be hydrated —
                    # report the shard failed instead of silently
                    # returning fewer hits
                    METRICS.counter("dist.shard_failed").inc()
                    failures[s_id] = {
                        "type": getattr(err, "kind",
                                        "node_unreachable"),
                        "node": owner,
                        "attempts": getattr(err, "attempts", 1)}
                    fetched = []
                else:
                    # local-leg errors propagate exactly as the serial
                    # (un-tried) local branch did
                    raise err
                for c, h in zip(sel, fetched):
                    hits_by_key[(c.shard, c.seg_ord, c.local_doc)] = h
        hits = [hits_by_key[(c.shard, c.seg_ord, c.local_doc)]
                for c in reduced["selected"]
                if (c.shard, c.seg_ord, c.local_doc) in hits_by_key]
        for h in hits:
            h["_index"] = index

        track = body.get("track_total_hits", True)
        total, relation = reduced["total"], reduced.get("total_rel", "eq")
        if track is not True and track is not False:
            track_n = int(track)
            if total > track_n:
                total, relation = track_n, "gte"
        timed_out = rs.timed_out or any(
            getattr(r, "timed_out", False) for r in merged)
        terminated_early = any(getattr(r, "terminated_early", False)
                               for r in merged)
        failed_list = [{"shard": s, "node": f.get("node"),
                        "reason": {k: v for k, v in f.items()
                                   if k != "node"}}
                       for s, f in sorted(failures.items())]
        if body.get("allow_partial_search_results", True) is False \
                and (failed_list or timed_out):
            # reference parity: partial results refused -> the whole
            # request fails (SearchPhaseExecutionException shape)
            raise ApiError(
                503, "search_phase_execution_exception",
                f"{len(failed_list)} shard failure(s)"
                f"{' and a timeout' if timed_out else ''} with "
                f"allow_partial_search_results=false")
        resp = {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": timed_out,
            "_shards": {"total": n_shards,
                        "successful": n_shards - len(failed_list),
                        "skipped": 0, "failed": len(failed_list),
                        **({"failures": failed_list}
                           if failed_list else {})},
            "hits": {"total": {"value": total, "relation": relation},
                     "max_score": (reduced["max_score"]
                                   if reduced["max_score"] != float("-inf")
                                   else None),
                     "hits": hits},
        }
        if terminated_early:
            resp["terminated_early"] = True
        if reduced["aggs"]:
            resp["aggregations"] = reduced["aggs"]
        return resp

    # ---------------- fleet observability federation ----------------
    #
    # `GET /_cluster/stats`, `_nodes/stats`, `_nodes/{id}/hot_threads`
    # and `_nodes/stats/history` fan out over the same `/_internal` RPC
    # plane the search phases ride (docs/OBSERVABILITY.md "fleet"):
    # counters SUM cluster-wide, gauges roll up PER NODE, and DDSketch
    # histograms merge bin-wise (`utils/metrics.merge_sketches`) so
    # fleet p50/p95/p99 come from ONE merged sketch — never from
    # averaged per-node percentiles. Scrape failures degrade honestly:
    # an unreachable member contributes a per-node `failed` entry and
    # the `_nodes` rollup counts it; the coordinator never stalls past
    # the scrape cap (deadline-ctx rides the scrape like any RPC).

    def _obs_reg(self):
        if self.obs_registry is not None:
            return self.obs_registry
        from ..utils.metrics import METRICS
        return METRICS

    def _handle_obs(self, op: str, body: dict) -> dict:
        """Serving side of a fleet scrape (`/_internal/{stats,node_stats,
        hot_threads,history}`)."""
        if op == "stats":
            return {"node": self.name,
                    "wire": self._obs_reg().to_wire(),
                    "indices": self.client.indices_summary()}
        if op == "node_stats":
            local = self.client.nodes_stats()
            block = local["nodes"].get(self.node.node_name) or {}
            return {"node": self.name, "stats": block}
        if op == "indexing":
            # this node's `indexing.*` registry slice in wire form — the
            # coordinator sums counters/gauges and MERGES the sketches
            # (obs/ingest_obs.merge_parts), so fleet refresh-to-visible
            # percentiles come from one merged sketch
            from ..obs import ingest_obs as _iobs
            return {"node": self.name,
                    "parts": _iobs.local_parts(self._obs_reg())}
        if op == "hot_threads":
            from ..obs.hot_threads import hot_threads as _ht
            return {"node": self.name, "result": _ht(
                node_name=self.name,
                snapshots=int(body.get("snapshots", 3)),
                interval_s=float(body.get("interval_ms", 20)) / 1000.0,
                ignore_idle=bool(body.get("ignore_idle", True)),
                as_json=bool(body.get("as_json", False)))}
        if op == "insights":
            w = body.get("window_s")
            return {"node": self.name,
                    "wire": self._insights().to_wire(
                        window_s=float(w) if w is not None else None)}
        if op == "remediation":
            return {"node": self.name, "status": "ok",
                    **self._remediation().status()}
        # history
        from ..obs.timeseries import SAMPLER
        return {"node": self.name,
                "history": SAMPLER.history(
                    str(body.get("metric") or ""),
                    float(body.get("window_s", 60.0)))}

    def _insights(self):
        if self.insights_engine is not None:
            return self.insights_engine
        from ..obs.insights import INSIGHTS
        return INSIGHTS

    def _remediation(self):
        if self.remediation_engine is not None:
            return self.remediation_engine
        from ..serving.remediator import REMEDIATOR
        return REMEDIATOR

    def _scrape_timeout_s(self) -> float:
        dl = _dl.current()
        cap = min(_RPC_TIMEOUT_CAP_S, _SCRAPE_CAP_S)
        return dl.rpc_timeout_s(cap) if dl is not None else cap

    def _scrape(self, op: str, payload: dict,
                members: Optional[List[str]] = None) -> Dict[str, tuple]:
        """Fan one obs RPC out CONCURRENTLY; returns member ->
        ("ok", result) or ("failed", reason). The self leg never crosses
        the wire. Remote legs run on per-member threads carrying the
        caller's context (deadline/trace/obs ctx ride each scrape), so
        the whole fan-out is bounded by ONE scrape timeout — k wedged
        members cost max(cap), not k*cap (utils/legs.py)."""
        from ..utils.metrics import METRICS
        want = sorted(members if members is not None else self.members)
        timeout_s = self._scrape_timeout_s()

        def leg(member: str) -> tuple:
            if member == self.name:
                return ("ok", self._handle_obs(op, payload))
            try:
                return ("ok", self._rpc(member, op, payload,
                                        timeout_s=timeout_s))
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                METRICS.counter("dist.scrape.failed").inc()
                return ("failed", f"{type(e).__name__}: {e}"[:200])

        ls = _legs.LegSet(f"dist.scrape.{op}")
        for member in want:
            ls.add_leg(lambda m=member: leg(m), name=member)
        out: Dict[str, tuple] = {}
        for member, leg_out in zip(want, ls.join(timeout_s=timeout_s
                                                 + _legs.JOIN_GRACE_S)):
            if leg_out.error is not None:
                out[member] = ("failed",
                               f"{type(leg_out.error).__name__}: "
                               f"{leg_out.error}"[:200])
            else:
                out[member] = leg_out.value
        return out

    def _resolve_member(self, node_id: Optional[str]) -> List[str]:
        """`_nodes/{id}/...` member filter. `_all`/`_local`/None keep
        reference semantics; an unknown id is a 404, never a silent
        coordinator-only answer."""
        if node_id in (None, "_all"):
            return sorted(self.members)
        if node_id == "_local":
            return [self.name]
        if node_id in self.members:
            return [node_id]
        raise ApiError(404, "resource_not_found_exception",
                       f"no such node [{node_id}]")

    def cluster_stats(self) -> dict:
        """`GET /_cluster/stats`: the fleet rollup. Counters sum, gauges
        stay per-node, histograms merge into true fleet percentiles,
        index totals sum over exactly the members that answered."""
        from ..utils.metrics import merge_sketches, sketch_snapshot
        scraped = self._scrape("stats", {})
        nodes: Dict[str, dict] = {}
        counters: Dict[str, float] = {}
        hist_wires: Dict[str, list] = {}
        indices = {"docs": 0, "store_in_bytes": 0, "segments": 0}
        ok = 0
        for member, (status, res) in scraped.items():
            if status != "ok":
                nodes[member] = {"status": "failed", "error": res}
                continue
            ok += 1
            wire = res.get("wire") or {}
            nodes[member] = {"status": "ok",
                             "gauges": wire.get("gauges", {}),
                             "counters": wire.get("counters", {}),
                             "indices": res.get("indices", {})}
            for k, v in (wire.get("counters") or {}).items():
                counters[k] = counters.get(k, 0) + v
            for k, w in (wire.get("histograms") or {}).items():
                hist_wires.setdefault(k, []).append(w)
            for k in indices:
                indices[k] += int((res.get("indices") or {}).get(k, 0))
        merged = {k: merge_sketches(ws)
                  for k, ws in sorted(hist_wires.items())}
        return {
            "cluster_name": self.node.metadata.cluster_name,
            "coordinator": self.name,
            "_nodes": {"total": len(scraped), "successful": ok,
                       "failed": len(scraped) - ok},
            "nodes": nodes,
            "indices": indices,
            "counters": dict(sorted(counters.items())),
            # fleet percentiles FROM MERGED SKETCHES (the per-node
            # sketches are also returned so a reader can re-derive)
            "percentiles": {k: sketch_snapshot(w)
                            for k, w in merged.items()},
            "histograms": merged,
        }

    def indexing_stats(self) -> dict:
        """`GET /_nodes/stats/indexing` federated: scrape every member's
        `indexing.*` wire parts, fold them (counters and gauges sum —
        the fleet writer buffer is the sum of node buffers; DDSketch
        histograms merge bin-wise), then assemble the SAME block shape
        one node serves (obs/ingest_obs.assemble_block). Percentiles are
        computed from the merged sketch, never averaged. Unreachable
        members degrade to `failed` entries in `_nodes`."""
        from ..obs import ingest_obs as _iobs
        scraped = self._scrape("indexing", {})
        parts = []
        nodes = {}
        ok = 0
        for member, (status, res) in scraped.items():
            if status == "ok":
                ok += 1
                parts.append(res.get("parts") or {})
                nodes[member] = {"status": "ok"}
            else:
                nodes[member] = {"status": "failed", "error": res}
        block = _iobs.assemble_block(_iobs.merge_parts(parts), nodes=ok)
        return {"cluster_name": self.node.metadata.cluster_name,
                "coordinator": self.name,
                "_nodes": {"total": len(scraped), "successful": ok,
                           "failed": len(scraped) - ok},
                "nodes": nodes,
                "indexing": block}

    def nodes_stats_federated(self, node_id: Optional[str] = None
                              ) -> dict:
        """`GET /_nodes[/{id}]/stats` with node fan-out: each targeted
        member's full per-node stats block under its cluster member
        name; unreachable members degrade to `{"failed": ...}` entries,
        an unknown id is a 404 (never a silent whole-fleet answer)."""
        scraped = self._scrape("node_stats", {},
                               self._resolve_member(node_id))
        nodes = {}
        ok = 0
        for member, (status, res) in scraped.items():
            if status == "ok":
                ok += 1
                nodes[member] = res.get("stats") or {}
            else:
                nodes[member] = {"failed": res}
        return {"cluster_name": self.node.metadata.cluster_name,
                "_nodes": {"total": len(scraped), "successful": ok,
                           "failed": len(scraped) - ok},
                "nodes": nodes}

    def hot_threads_federated(self, node_id: Optional[str] = None,
                              snapshots: int = 3,
                              interval_ms: float = 20.0,
                              ignore_idle: bool = True,
                              as_json: bool = False):
        """`GET /_nodes[/{id}]/hot_threads` across the cluster: per-node
        sections (each member samples ITS OWN process — before this,
        the coordinator silently sampled only itself), unreachable
        members as explicit failed sections."""
        members = self._resolve_member(node_id)
        payload = {"snapshots": int(snapshots),
                   "interval_ms": float(interval_ms),
                   "ignore_idle": bool(ignore_idle),
                   "as_json": bool(as_json)}
        scraped = self._scrape("hot_threads", payload, members)
        if as_json:
            return {"nodes": {
                m: ({"threads": res.get("result")} if status == "ok"
                    else {"failed": res})
                for m, (status, res) in scraped.items()}}
        parts = []
        for m, (status, res) in scraped.items():
            if status == "ok":
                parts.append(str(res.get("result")))
            else:
                parts.append(f"::: {{{m}}}\n   <hot_threads scrape "
                             f"failed: {res}>\n")
        return "".join(parts)

    def history_federated(self, metric: str, window_s: float = 60.0,
                          node_id: Optional[str] = None) -> dict:
        """`GET /_nodes[/{id}]/stats/history`: each member's local
        time-series window for one metric (obs/timeseries.py)."""
        members = self._resolve_member(node_id)
        scraped = self._scrape(
            "history", {"metric": metric, "window_s": float(window_s)},
            members)
        nodes = {}
        ok = 0
        for m, (status, res) in scraped.items():
            if status == "ok":
                ok += 1
                nodes[m] = res.get("history") or {}
            else:
                nodes[m] = {"failed": res}
        return {"metric": metric, "window_s": float(window_s),
                "_nodes": {"total": len(scraped), "successful": ok,
                           "failed": len(scraped) - ok},
                "nodes": nodes}

    def top_queries_federated(self, by: str = "latency", n: int = 10,
                              window_s: Optional[float] = None,
                              node_id: Optional[str] = None) -> dict:
        """`GET /_insights/top_queries` on a cluster: every member's
        heavy-hitter sketch wire merges through the commutative
        space-saving merge (`obs/insights.py merge_wires`), so the
        fleet's top-N is computed from ONE merged summary — never from
        concatenated per-node top lists (which under-rank a shape that
        is #11 everywhere but #1 fleet-wide). Unreachable members
        degrade to per-node `failed` entries, the merge covers whoever
        answered."""
        from ..obs import insights as _ins
        if by not in _ins.TOP_BY:
            raise ApiError(400, "illegal_argument_exception",
                           f"unknown top_queries ranking [{by}] "
                           f"(one of {_ins.TOP_BY})")
        payload = ({"window_s": float(window_s)}
                   if window_s is not None else {})
        scraped = self._scrape("insights", payload,
                               self._resolve_member(node_id))
        wires = []
        nodes: Dict[str, dict] = {}
        ok = 0
        for member, (status, res) in scraped.items():
            if status == "ok":
                ok += 1
                wires.append(res.get("wire") or {})
                nodes[member] = {"status": "ok"}
            else:
                nodes[member] = {"status": "failed", "error": res}
        cap = self._insights().capacity
        n = max(int(n), 0)     # the QueryInsights.top clamp, mirrored
        if window_s is not None:
            merged = _ins.merge_windowed_wires(wires, cap,
                                               float(window_s))
            top = sorted(merged["entries"],
                         key=_ins.QueryInsights._rank_key(by))[:n]
        else:
            merged = _ins.merge_wires(wires, cap)
            top = sorted((_ins._derived(d) for d in merged["entries"]),
                         key=_ins.QueryInsights._rank_key(by))[:n]
        return {"by": by, "n": int(n),
                **({"window_s": float(window_s)}
                   if window_s is not None else {}),
                "capacity": cap,
                "total_records": merged["total_records"],
                "_nodes": {"total": len(scraped), "successful": ok,
                           "failed": len(scraped) - ok},
                "nodes": nodes,
                "top_queries": top}

    def remediation_federated(self, node_id: Optional[str] = None
                              ) -> dict:
        """`GET /_remediation` on a cluster: every member's live action
        table + engage/release counters, fanned out on the `/_internal`
        plane with the standard unreachable-member degradation — the
        operator's one-stop "what is the fleet doing to itself right
        now" pane."""
        scraped = self._scrape("remediation", {},
                               self._resolve_member(node_id))
        nodes: Dict[str, dict] = {}
        ok = 0
        active_total = 0
        for member, (status, res) in scraped.items():
            if status == "ok":
                ok += 1
                nodes[member] = {k: v for k, v in res.items()
                                 if k != "node"}
                active_total += len(res.get("active") or [])
            else:
                nodes[member] = {"status": "failed", "error": res}
        return {"_nodes": {"total": len(scraped), "successful": ok,
                           "failed": len(scraped) - ok},
                "active_actions_total": active_total,
                "nodes": nodes}

    # ---------------- lifecycle + stats ----------------

    def resilience_stats(self) -> dict:
        """This node's failure-domain view: member detector state + the
        retry policy in force (the counter rollup lives in
        `_nodes/stats` "resilience" and `/_metrics`)."""
        p = self.retry_policy
        return {"member_detector": self.member_fd.stats(),
                "retry_policy": {
                    "same_member_retries": p.same_member_retries,
                    "budget": p.budget,
                    "base_backoff_s": p.base_backoff_s,
                    "max_backoff_s": p.max_backoff_s,
                    "storm_n": p.storm_n},
                "rpc_timeout_cap_s": _RPC_TIMEOUT_CAP_S}

    def stop(self) -> None:
        self.server.stop()
