"""Seeded deterministic fault injection for the distributed serving tier
(docs/RESILIENCE.md).

The resilience machinery in `cluster/distnode.py` — deadline propagation,
per-shard retry with replica failover, the hardened partial-results
contract — is only trustworthy if exact failure interleavings can be
REPLAYED. This module is the injection layer: a `ChaosSchedule` holds an
ordered rule list; every rule matches an injection site deterministically,
fires a bounded number of times, and appends what it did to a journal.

Determinism under PARALLEL LEGS (utils/legs.py): per-rule call counters
are keyed by the call's stable identity `(op, member, leg path)` — a
pure function of request structure — and probabilistic draws derive
from `sha256(seed | rule | site | identity | call#)` instead of a shared
RNG stream consumed in arrival order. Thread interleaving can therefore
never change WHICH calls a rule fires on, and the `journal` property
returns entries in a canonical total order rather than arrival order.
Same schedule + same call set -> byte-identical journal, serial or
parallel, which is what the tier-1 replay tests assert.

Injection sites (the hooks live in product code, behind an `enabled()`
fast path that is one module-global read when no schedule is installed):

- `rpc.send`    — coordinator side of every `/_internal` RPC
                  (`DistClusterNode._rpc`), keyed by target member + op
- `rpc.recv`    — serving side (`DistClusterNode.handle_internal`)
- `sched.complete` — the serving scheduler's completion stage
                  (slow-fetch injection; serving/scheduler.py)

Actions:

- `drop`       — raise `FaultInjected` (an OSError: looks like a refused
                 connection to the retry machinery)
- `delay`      — sleep `delay_s`, then proceed (slow node / slow fetch)
- `error`      — raise `FaultInjected` tagged as a remote 5xx
- `blackhole`  — sleep the CALLER's deadline-derived RPC timeout (capped
                 by `delay_s`), then raise `FaultTimeout` — the
                 wire-level signature of a hung peer, without ever
                 holding a test for the full 30 s transport cap
- `breaker_trip` — raise CircuitBreakingException at the site

Node-level helpers compose these: `kill_node(m)` black-holes every
future send to `m` instantly (drop), `pause_node(m, s)` delays them.

This is a test/bench surface: nothing here is imported on the serving
hot path unless a schedule is installed, and `install()` is explicit.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

_SITES = ("rpc.send", "rpc.recv", "sched.complete")
_ACTIONS = ("drop", "delay", "error", "blackhole", "breaker_trip")

# cap on how long a blackhole may hold a call when the caller has no
# deadline (tests must never stall for the full transport cap)
_BLACKHOLE_CAP_S = 2.0


class FaultInjected(OSError):
    """An injected transport-level fault (drop / remote error)."""

    def __init__(self, site: str, action: str, member=None, op=None):
        super().__init__(f"chaos[{action}] at {site} "
                         f"(member={member}, op={op})")
        self.site = site
        self.action = action
        self.member = member
        self.op = op


class FaultTimeout(FaultInjected, TimeoutError):
    """An injected hang: the call 'waited' its full timeout and died."""


class _Rule:
    __slots__ = ("site", "action", "op", "member", "at", "after", "times",
                 "delay_s", "p", "calls", "fired", "calls_by_key")

    def __init__(self, site: str, action: str, op: Optional[str],
                 member: Optional[str], at, after: Optional[int],
                 times: Optional[int], delay_s: float, p: Optional[float]):
        if site not in _SITES:
            raise ValueError(f"unknown chaos site [{site}]")
        if action not in _ACTIONS:
            raise ValueError(f"unknown chaos action [{action}]")
        self.site = site
        self.action = action
        self.op = op                    # None = any op
        self.member = member            # None = any member/node
        # 1-based matching-call indexes, counted PER call identity
        # (op, member, leg path) so parallel legs can't perturb them
        self.at = set(at) if at else None
        if after is None and self.at is None and p is None:
            # a rule with no selector means "every matching call" —
            # without this default it would match forever and never
            # fire, passing chaos tests vacuously
            after = 1
        self.after = after              # fire on every call >= after
        self.times = times              # max fires (None = unbounded)
        self.delay_s = float(delay_s)
        self.p = p                      # probability (seeded rng)
        self.calls = 0                  # matching calls seen (total)
        self.fired = 0
        # matching calls per stable call identity (op, member, leg
        # path): the counter parallel legs cannot perturb
        self.calls_by_key: Dict[tuple, int] = {}

    def describe(self) -> dict:
        return {"site": self.site, "action": self.action, "op": self.op,
                "member": self.member,
                "at": sorted(self.at) if self.at else None,
                "after": self.after, "times": self.times, "p": self.p,
                "delay_s": self.delay_s, "fired": self.fired}


class ChaosSchedule:
    """An ordered, seeded fault plan. Rules are evaluated in add() order;
    the FIRST matching rule that decides to fire wins the call."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self.rules: List[_Rule] = []
        self._journal: List[dict] = []   # arrival order (diagnostics)
        self._seq = 0

    @property
    def journal(self) -> List[dict]:
        """Fired-fault records in CANONICAL order — sorted by (rule,
        site, op, member, leg, call), not thread arrival order — with
        `seq` recomputed as the canonical position. This is the replay
        artifact: byte-identical across reruns and across the
        serial/parallel legs arms (arrival order is not; use
        `journal_arrivals()` for diagnostics)."""
        with self._lock:
            recs = list(self._journal)
        recs.sort(key=lambda e: (e["rule"], e["site"], e["op"] or "",
                                 e["member"] or "", e.get("leg") or "",
                                 e["call"]))
        return [{**e, "seq": i + 1} for i, e in enumerate(recs)]

    def journal_arrivals(self) -> List[dict]:
        """The journal in raw arrival order (nondeterministic under
        parallel legs — never asserted on, useful when debugging an
        interleaving)."""
        with self._lock:
            return list(self._journal)

    # ---------------- plan construction ----------------

    def add(self, site: str, action: str, op: Optional[str] = None,
            member: Optional[str] = None, at=None,
            after: Optional[int] = None, times: Optional[int] = None,
            delay_s: float = 0.05,
            p: Optional[float] = None) -> "ChaosSchedule":
        self.rules.append(_Rule(site, action, op, member, at, after,
                                times, delay_s, p))
        return self

    def kill_node(self, member: str) -> "ChaosSchedule":
        """Every future send to `member` fails instantly (SIGKILL shape:
        connection refused, no partial responses)."""
        return self.add("rpc.send", "drop", member=member, after=1)

    def pause_node(self, member: str, delay_s: float) -> "ChaosSchedule":
        """Every future send to `member` stalls `delay_s` then proceeds
        (GC pause / overloaded-node shape)."""
        return self.add("rpc.send", "delay", member=member, after=1,
                        delay_s=delay_s)

    # ---------------- firing ----------------

    def _draw(self, rule_idx: int, site: str, key: tuple,
              call: int) -> float:
        """Uniform [0,1) derived from the call's stable identity —
        hashlib, NOT Python hash() (PYTHONHASHSEED-randomized) and NOT
        a shared stream (arrival-order-dependent). Replays and the
        serial/parallel arms see identical draws for identical calls."""
        import hashlib
        h = hashlib.sha256(
            f"{self.seed}|{rule_idx}|{site}|{key[0]}|{key[1]}|{key[2]}|"
            f"{call}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64

    def fire(self, site: str, op: Optional[str] = None,
             member: Optional[str] = None) -> Optional[dict]:
        """Consult the plan for one call at `site`. Returns the action
        record to apply (journaled), or None. Deterministic under
        concurrency: per-rule matching-call counters and probability
        draws are keyed by the call's stable identity (op, member, leg
        path) — thread interleaving cannot change which calls fire.
        The one order-sensitive residue: a `times`-capped rule whose
        selector hits on SEVERAL identities racing in the same round
        fires on whichever acquires the lock first; keep `times` rules
        keyed to a specific member/op for byte-stable replay."""
        from ..utils import legs as _legs
        key = (op, member, _legs.current_path())
        with self._lock:
            for idx, r in enumerate(self.rules):
                if r.site != site:
                    continue
                if r.op is not None and r.op != op:
                    continue
                if r.member is not None and r.member != member:
                    continue
                r.calls += 1
                n = r.calls_by_key.get(key, 0) + 1
                r.calls_by_key[key] = n
                if r.times is not None and r.fired >= r.times:
                    continue
                hit = False
                if r.at is not None:
                    hit = n in r.at
                elif r.after is not None:
                    hit = n >= r.after
                if r.p is not None:
                    draw = self._draw(idx, site, key, n)
                    hit = (hit or (r.at is None and r.after is None)) \
                        and draw < r.p
                if not hit:
                    continue
                r.fired += 1
                self._seq += 1
                rec = {"seq": self._seq, "rule": idx, "site": site,
                       "op": op, "member": member, "leg": key[2],
                       "action": r.action, "call": n,
                       "delay_s": r.delay_s}
                self._journal.append(rec)
                return rec
        return None

    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed, "fired": self._seq,
                    "rules": [r.describe() for r in self.rules]}


# ---------------------------------------------------------------------
# module-global installation + site hooks
# ---------------------------------------------------------------------

_INSTALLED: Optional[ChaosSchedule] = None


def install(schedule: ChaosSchedule) -> ChaosSchedule:
    global _INSTALLED
    _INSTALLED = schedule
    return schedule


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = None


def enabled() -> bool:
    return _INSTALLED is not None


def installed() -> Optional[ChaosSchedule]:
    return _INSTALLED


def stats() -> dict:
    sched = _INSTALLED
    return {"installed": sched is not None,
            **(sched.stats() if sched is not None else {})}


def _apply(rec: dict, site: str, member, op,
           timeout_s: Optional[float]) -> None:
    from ..utils.metrics import METRICS
    METRICS.counter(f"chaos.{rec['action']}").inc()
    action = rec["action"]
    if action == "delay":
        time.sleep(rec["delay_s"])
        return
    if action == "drop":
        raise FaultInjected(site, action, member, op)
    if action == "error":
        raise FaultInjected(site, "error", member, op)
    if action == "blackhole":
        # hold the call exactly as long as a hung peer would: the
        # caller's own (deadline-derived) timeout, never more than the
        # rule's cap — then die the way a socket timeout dies
        hold = min(timeout_s if timeout_s is not None else _BLACKHOLE_CAP_S,
                   rec["delay_s"] if rec["delay_s"] > 0
                   else _BLACKHOLE_CAP_S)
        time.sleep(max(hold, 0.0))
        raise FaultTimeout(site, action, member, op)
    if action == "breaker_trip":
        from ..utils.breaker import CircuitBreakingException
        raise CircuitBreakingException(f"chaos[breaker_trip] at {site}")


def on_rpc_send(member: str, op: str,
                timeout_s: Optional[float] = None) -> None:
    """Coordinator-side hook: called before the wire write of every
    `/_internal` RPC."""
    sched = _INSTALLED
    if sched is None:
        return
    rec = sched.fire("rpc.send", op=op, member=member)
    if rec is not None:
        _apply(rec, "rpc.send", member, op, timeout_s)


def on_rpc_recv(node: str, op: str) -> None:
    """Serving-side hook: called as the `/_internal` handler accepts."""
    sched = _INSTALLED
    if sched is None:
        return
    rec = sched.fire("rpc.recv", op=op, member=node)
    if rec is not None:
        _apply(rec, "rpc.recv", node, op, None)


def on_sched_complete(node: str) -> None:
    """Serving-scheduler completion-stage hook (slow fetch / wedge
    shapes; serving/scheduler.py)."""
    sched = _INSTALLED
    if sched is None:
        return
    rec = sched.fire("sched.complete", member=node)
    if rec is not None:
        _apply(rec, "sched.complete", node, None, None)
