"""Index administration: dynamic settings updates, open/close, and the
resize family (shrink / split / clone).

Reference analogs:
- `action/admin/indices/settings/put/TransportUpdateSettingsAction.java` +
  the dynamic/static split of `common/settings/IndexScopedSettings.java`
- `action/admin/indices/close/TransportCloseIndexAction.java`,
  `.../open/TransportOpenIndexAction.java` (verify-before-close, block
  semantics, wildcard handling)
- `action/admin/indices/shrink/TransportResizeAction.java` (shard-count
  factor rules, source write-block requirement, settings/mapping carry)
- `action/admin/cluster/settings/TransportClusterUpdateSettingsAction.java`

TPU-design notes: settings changes are host-side metadata operations — the
only device-visible effects are replica rebuilds (number_of_replicas) and
the write-block flag the fastpath's immutable segments already respect.
Resize re-routes documents by `_id` through the target's write path and
then force-merges, so the final segment build runs the device merge sort
(`ops/device_merge.py`); the reference's hard-link recovery optimization
is not replicated (documents are re-indexed; custom `_routing` values are
not persisted per doc and therefore not preserved).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .state import ClusterStateError, IndexNotFoundError


class IndexClosedError(ClusterStateError):
    """HTTP 400 index_closed_exception analog."""


class SettingsError(ClusterStateError):
    """HTTP 400 illegal_argument_exception analog for settings updates."""


# dynamic settings: updatable on an open index (reference
# IndexScopedSettings dynamic registrations — the subset this engine
# implements behavior for, plus passthrough knobs that only need storage)
_DYNAMIC_EXACT = {
    "number_of_replicas",
    "refresh_interval",
    "max_result_window",
    "max_inner_result_window",
    "default_pipeline",
    "final_pipeline",
    "search.default_pipeline",
    "blocks.read_only",
    "blocks.read_only_allow_delete",
    "blocks.read",
    "blocks.write",
    "blocks.metadata",
    "highlight.max_analyzed_offset",
    "requests.cache.enable",
}
_DYNAMIC_PREFIXES = (
    "search.slowlog.",
    "indexing.slowlog.",
    "routing.allocation.",
    "lifecycle.",
)

# static settings: fixed after index creation; updatable only while the
# index is CLOSED (reference allows e.g. analysis updates on closed
# indices). `final` settings can never change.
_FINAL = {"number_of_shards", "uuid", "creation_date", "version.created",
          "routing_partition_size"}
_STATIC_PREFIXES = ("analysis.", "similarity.", "sort.", "merge.")
_STATIC_EXACT = {"codec", "knn"}


def _flatten(settings: dict, prefix: str = "") -> Dict[str, object]:
    """{"index": {"blocks": {"write": true}}} -> {"blocks.write": True};
    accepts pre-flattened dotted keys and a leading "index." prefix."""
    out: Dict[str, object] = {}
    for k, v in (settings or {}).items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{key}."))
        else:
            out[key] = v
    return {k[6:] if k.startswith("index.") else k: v
            for k, v in out.items()}


def _classify(key: str) -> str:
    if key in _FINAL:
        return "final"
    if key in _DYNAMIC_EXACT or key.startswith(_DYNAMIC_PREFIXES):
        return "dynamic"
    if key in _STATIC_EXACT or key.startswith(_STATIC_PREFIXES):
        return "static"
    return "unknown"


def _set_nested(d: dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    for p in parts[:-1]:
        nxt = d.get(p)
        if not isinstance(nxt, dict):
            nxt = d[p] = {}
        d = nxt
    d[parts[-1]] = value


def update_index_settings(node, expression: str, body: dict,
                          preserve_existing: bool = False) -> dict:
    """PUT /{index}/_settings with dynamic-vs-static validation."""
    flat = _flatten(body.get("settings", body))
    names = node.metadata.resolve(expression, allow_no_indices=False)
    # validate against every target first (all-or-nothing, like the
    # reference's single cluster-state update)
    for name in names:
        svc = node.indices[name]
        closed = svc.meta.state == "close"
        for key, value in flat.items():
            cls = _classify(key)
            if cls == "final":
                raise SettingsError(
                    f"final index setting [index.{key}], not updateable")
            if cls == "static" and not closed:
                raise SettingsError(
                    f"Can't update non dynamic settings [[index.{key}]] "
                    f"for open indices [[{name}]]")
            if cls == "unknown":
                raise SettingsError(
                    f"unknown setting [index.{key}]")
            if key == "number_of_replicas" and int(value) < 0:
                raise SettingsError("number_of_replicas must be >= 0")
    for name in names:
        svc = node.indices[name]
        with svc.write_lock:
            idx = svc.meta.settings.setdefault("index", {})
            for key, value in flat.items():
                if preserve_existing and _has_nested(idx, key):
                    continue
                _set_nested(idx, key, value)
            _apply_effects(node, svc, flat)
            node._persist_meta(name)
    return {"acknowledged": True}


def _has_nested(d: dict, dotted: str) -> bool:
    for p in dotted.split("."):
        if not isinstance(d, dict) or p not in d:
            return False
        d = d[p]
    return True


def _apply_effects(node, svc, flat: Dict[str, object]) -> None:
    from ..utils.slowlog import SlowLog

    if "number_of_replicas" in flat and svc.meta.state != "close":
        svc._init_replicas()
        svc.generation += 1
    if any(k.startswith("search.slowlog.") for k in flat):
        svc.search_slowlog = SlowLog(svc.meta.name, svc.meta.settings,
                                     "search", "query")
    if any(k.startswith("indexing.slowlog.") for k in flat):
        svc.index_slowlog = SlowLog(svc.meta.name, svc.meta.settings,
                                    "indexing", "index")


def close_index(node, expression: str) -> dict:
    """POST /{index}/_close: flush for durability (the reference's
    verify-before-close), then mark closed — searches and writes reject
    with index_closed_exception until reopened."""
    names = node.metadata.resolve(expression, allow_no_indices=False)
    for name in names:
        svc = node.indices[name]
        if svc.meta.state == "close":
            continue
        # metadata-class transition: drain writers, exclude other
        # metadata ops (node.py meta_lock contract)
        with node.meta_lock, svc.write_lock:
            svc.flush()
            svc.meta.state = "close"
            node._persist_meta(name)
    return {"acknowledged": True, "shards_acknowledged": True,
            "indices": {n: {"closed": True} for n in names}}


def open_index(node, expression: str) -> dict:
    names = node.metadata.resolve(expression, allow_no_indices=False)
    for name in names:
        svc = node.indices[name]
        if svc.meta.state != "close":
            continue
        with node.meta_lock, svc.write_lock:
            svc.meta.state = "open"
            # static settings may have changed while closed (analysis
            # etc.): rebuild the service like recovery does
            node._reopen_service(name)
    return {"acknowledged": True, "shards_acknowledged": True}


def check_open(node, names: List[str], expression) -> List[str]:
    """Filter closed indices out of wildcard resolutions; explicitly named
    closed indices raise (reference IndicesOptions default: wildcards
    expand to open only, concrete names must be open)."""
    explicit = set()
    if expression not in (None, "", "_all", "*"):
        exprs = (expression if isinstance(expression, list)
                 else str(expression).split(","))
        for e in exprs:
            e = e.strip()
            if "*" in e or "?" in e:
                continue
            explicit.add(e)
            if e not in node.indices:
                # alias / data-stream token: the reference treats its
                # concrete backing indices as explicitly named too
                try:
                    explicit.update(node.metadata.resolve(e))
                except Exception:
                    pass
    out = []
    for n in names:
        svc = node.indices.get(n)
        if svc is not None and svc.meta.state == "close":
            if n in explicit:
                raise IndexClosedError(f"closed index [{n}]")
            continue
        out.append(n)
    return out


def resize_index(node, source: str, target: str, kind: str,
                 body: Optional[dict] = None) -> dict:
    """_shrink / _split / _clone. Shard-count rules follow the reference
    (murmur3 routing factor property): shrink needs a divisor, split a
    multiple, clone the same count. Source must be write-blocked."""
    body = body or {}
    if source not in node.indices:
        raise IndexNotFoundError(f"no such index [{source}]")
    if target in node.indices:
        raise SettingsError(f"index [{target}] already exists")
    svc = node.indices[source]
    if svc.meta.state == "close":
        raise IndexClosedError(f"closed index [{source}]")
    idx_settings = svc.meta.settings.get("index", {})
    blocks = idx_settings.get("blocks", {})
    if not (_truthy(blocks.get("write")) or _truthy(blocks.get("read_only"))):
        raise SettingsError(
            f"index {source} must be read-only to resize index. use "
            f"\"index.blocks.write=true\"")
    s_shards = svc.meta.num_shards
    tset = _flatten(body.get("settings", {}))
    t_shards = int(tset.get("number_of_shards",
                            1 if kind == "shrink" else s_shards))
    if kind == "shrink":
        if t_shards > s_shards or s_shards % t_shards:
            raise SettingsError(
                f"the number of source shards [{s_shards}] must be a "
                f"multiple of [{t_shards}]")
    elif kind == "split":
        if t_shards < s_shards or t_shards % s_shards:
            raise SettingsError(
                f"the number of target shards [{t_shards}] must be a "
                f"multiple of the source shards [{s_shards}]")
    elif t_shards != s_shards:
        raise SettingsError("clone must keep the source shard count")

    # target settings: source settings minus blocks, overridden by request
    # (deep-copied so nested overrides never write through to the source)
    import copy
    new_index = copy.deepcopy({k: v for k, v in idx_settings.items()
                               if k != "blocks"})
    new_index["number_of_shards"] = t_shards
    target_settings: dict = {"index": new_index}
    for key, value in tset.items():
        _set_nested(new_index, key, value)
    node.create_index(target, {"settings": target_settings,
                               "mappings": svc.mappings.to_dict()})
    tsvc = node.indices[target]
    svc.refresh()
    copied = 0
    for eng in svc.shards:
        for seg in eng.segments:
            for local in range(seg.ndocs):
                if not seg.live[local]:
                    continue
                doc_id = seg.ids[local]
                tsvc.route(doc_id).index_doc(doc_id, seg.sources[local])
                copied += 1
    tsvc.refresh()
    tsvc.force_merge(1)       # final build runs the device merge path
    for alias, cfg in (body.get("aliases") or {}).items():
        node._put_alias(alias, target, cfg or {})
    return {"acknowledged": True, "shards_acknowledged": True,
            "index": target, "copied_docs": copied}


def _truthy(v) -> bool:
    return v is True or v == "true" or v == 1


# ---------------------------------------------------------------------
# cluster settings (reference TransportClusterUpdateSettingsAction)
# ---------------------------------------------------------------------

_CLUSTER_DYNAMIC_PREFIXES = (
    "cluster.routing.allocation.",
    "cluster.blocks.",
    "indices.breaker.",
    "search.",
    "action.",
    "wlm.",
)


def update_cluster_settings(node, body: dict) -> dict:
    cs = node.__dict__.setdefault("cluster_settings", {})
    out = {"acknowledged": True, "persistent": {}, "transient": {}}
    for scope in ("persistent", "transient"):
        flat = _flatten(body.get(scope, {}) or {})
        for key, value in flat.items():
            if not key.startswith(_CLUSTER_DYNAMIC_PREFIXES):
                raise SettingsError(
                    f"unknown or non-dynamic cluster setting [{key}]")
            if value is None:
                cs.get(scope, {}).pop(key, None)   # null resets a setting
            else:
                cs.setdefault(scope, {})[key] = value
                out[scope][key] = value
            if key == "indices.breaker.fielddata.limit":
                _apply_breaker_limit(node, value)
    return out


def _apply_breaker_limit(node, value) -> None:
    try:
        breaker = node.breakers.breaker("fielddata")
    except Exception:
        return
    if isinstance(value, str) and value.endswith("%"):
        return                      # percent-of-heap n/a; store only
    try:
        breaker.limit = int(value)
    except (TypeError, ValueError):
        pass


def get_cluster_settings(node, include_defaults: bool = False) -> dict:
    cs = getattr(node, "cluster_settings", {})
    return {"persistent": dict(cs.get("persistent", {})),
            "transient": dict(cs.get("transient", {}))}
