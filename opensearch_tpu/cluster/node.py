"""The Node: owns indices (each = N shard engines + searchers), the ingest
service, caches, and breakers. Analog of reference `node/Node.java` +
`indices/IndicesService.java` + `index/IndexService.java`.

Shard layout is device-aware: with a `jax.sharding.Mesh` available, each
shard's segments are placed on the mesh device for its shard slot
(parallel/placement.py); on one chip all shards share it (still giving the
reference's concurrency-by-shard semantics for the API surface)."""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..analysis import AnalysisRegistry
from ..index.engine import Engine
from ..index.mappings import Mappings
from ..ingest import IngestService
from ..search import impactpath
from ..search.executor import ShardSearcher, msearch_batched, search_shards
from ..utils.breaker import BreakerService
from ..obs import flight_recorder as _fr
from ..obs import ingest_obs as _iobs
from ..utils.metrics import METRICS
from ..utils.slowlog import SlowLog
from ..utils.tasks import TaskRegistry
from ..utils.threadpool import ThreadPools
from .routing import shard_for
from .state import (ClusterMetadata, ClusterStateError, IndexMetadata,
                    IndexNotFoundError, ResourceAlreadyExistsError, AliasMetadata)


class IndexService:
    def __init__(self, meta: IndexMetadata, mapping: Optional[dict],
                 data_path: Optional[str] = None, thread_pools=None):
        self.meta = meta
        # remote-backed storage mirror (index/remote.py), attached by the
        # Node when a remote root is configured
        self.remote = None
        analysis = AnalysisRegistry(meta.settings.get("index", {}).get("analysis",
                                    meta.settings.get("analysis")))
        self.mappings = Mappings(mapping, analysis=analysis,
                                 dynamic=(mapping or {}).get("dynamic", True))
        sim_settings = meta.settings.get("index", {}).get("similarity",
                       meta.settings.get("similarity", {}))
        self.default_sim = sim_settings.get("default") if isinstance(sim_settings, dict) else None
        self.shards: List[Engine] = []
        self.searchers: List[ShardSearcher] = []
        for sid in range(meta.num_shards):
            path = os.path.join(data_path, meta.name, str(sid)) if data_path else None
            eng = Engine(self.mappings, path=path)
            eng.index_name = meta.name   # labels per-index write-path obs
            self.shards.append(eng)
            self.searchers.append(ShardSearcher(eng, shard_id=sid,
                                                similarity=self.default_sim,
                                                index_key=meta.name))
        self.generation = 0  # bumped on refresh/writes: request-cache key part
        # per-index write serialization (the analog of the reference's
        # per-shard engine write locks, InternalEngine.java:1): acquired by
        # the client layer AFTER alias/pipeline resolution, so every
        # transport (dict API, HTTP, dist) serializes mutations of this
        # index while writes to other indices proceed in parallel
        self.write_lock = threading.RLock()
        self.thread_pools = thread_pools
        self.search_slowlog = SlowLog(meta.name, meta.settings, "search",
                                      "query")
        self.index_slowlog = SlowLog(meta.name, meta.settings, "indexing",
                                     "index")
        self._init_replicas()

    def _init_replicas(self) -> None:
        """Allocate shard copies over devices and build replica shards
        (segment replication: replicas re-host the primary's immutable
        segments on their own device — cluster/replication.py)."""
        import jax

        from ..parallel.placement import ShardAllocator
        from .replication import ReplicaShard

        devices = jax.devices()
        self.allocator = ShardAllocator(len(devices))
        self.table = self.allocator.allocate(self.meta.num_shards,
                                             self.meta.num_replicas)
        self.replicas: Dict[Tuple[int, int], ReplicaShard] = {}
        self.replica_searchers: Dict[Tuple[int, int], ShardSearcher] = {}
        self._devices = devices
        for copy in self.table.copies:
            if copy.primary or copy.device is None:
                continue
            self._build_replica(copy)
        self._rr = 0

    def _build_replica(self, copy) -> None:
        from .replication import ReplicaShard

        dev = self._devices[copy.device]
        rep = ReplicaShard(self.shards[copy.shard], copy.shard,
                           copy.replica, device=dev)
        rep.sync(warm=False)  # adopt recovered/restored segments now
        self.replicas[(copy.shard, copy.replica)] = rep
        s = ShardSearcher(self.shards[copy.shard], shard_id=copy.shard,
                          similarity=self.default_sim,
                          index_key=self.meta.name, device=dev)
        s.replica = rep
        self.replica_searchers[(copy.shard, copy.replica)] = s

    def fail_device(self, device_ord: int) -> None:
        """Device (chip) failure: re-allocate its shard copies and rebuild
        the moved replicas on their new devices; a lost primary promotes a
        surviving replica first (reference allocation + promotion flow)."""
        lost_primaries = [c.shard for c in self.table.copies
                          if c.primary and c.device == device_ord]
        for sid in lost_primaries:
            try:
                self.fail_primary(sid)
            except ClusterStateError:
                # no replica to promote: the shard goes unassigned and the
                # index reports red (reference allocation on primary loss)
                pcopy = next(c for c in self.table.for_shard(sid)
                             if c.primary)
                pcopy.device = None
                pcopy.state = "UNASSIGNED"
        changed = self.allocator.fail_device(device_ord, self.table)
        for copy in changed:
            key = (copy.shard, copy.replica)
            self.replicas.pop(key, None)
            self.replica_searchers.pop(key, None)
            if not copy.primary and copy.device is not None:
                self._build_replica(copy)
        self.generation += 1

    def route(self, doc_id: str, routing: Optional[str] = None) -> Engine:
        return self.shards[shard_for(routing or doc_id, self.meta.num_shards)]

    def search_copies(self) -> List[ShardSearcher]:
        """One searcher per shard, round-robin across started copies
        (reference OperationRouting preference=round-robin replica fan-out)."""
        self._rr += 1
        out = []
        for sid in range(self.meta.num_shards):
            copies = [c for c in self.table.for_shard(sid)
                      if c.state == "STARTED"]
            if not copies:
                continue  # shard lost entirely -> partial results (red)
            pick = copies[self._rr % len(copies)]
            if pick.primary:
                out.append(self.searchers[sid])
            else:
                out.append(self.replica_searchers[(sid, pick.replica)])
        return out

    def fail_primary(self, shard_id: int) -> None:
        """Simulate primary loss: promote a started replica (segments it has
        already synced) and rebuild its searcher. Raises if no replica."""
        from .replication import promote_to_primary

        cand = [(k, r) for k, r in self.replicas.items()
                if k[0] == shard_id and r.state == "STARTED"]
        if not cand:
            raise ClusterStateError(
                f"no started replica to promote for shard [{shard_id}]")
        (key, rep) = cand[0]
        new_primary = promote_to_primary(self.mappings, rep,
                                         self.shards[shard_id].primary_term + 1)
        self.shards[shard_id] = new_primary
        self.searchers[shard_id] = ShardSearcher(
            new_primary, shard_id=shard_id, similarity=self.default_sim,
            index_key=self.meta.name, device=rep.device)
        # the promoted copy takes over the primary slot in the table;
        # remaining replicas track the new primary
        del self.replicas[key]
        del self.replica_searchers[key]
        pcopy = next(c for c in self.table.for_shard(shard_id) if c.primary)
        rcopy = next(c for c in self.table.for_shard(shard_id)
                     if c.replica == key[1])
        pcopy.device = rcopy.device
        pcopy.state = "STARTED"
        self.table.copies.remove(rcopy)
        for (sid, rid), r in self.replicas.items():
            if sid == shard_id:
                r.primary = new_primary
                r.sync()
                self.replica_searchers[(sid, rid)].engine = new_primary
        self.generation += 1

    def health_status(self) -> str:
        if any(c.state != "STARTED" and c.primary for c in self.table.copies):
            return "red"
        if any(c.state != "STARTED" for c in self.table.copies):
            return "yellow"
        return "green"

    def refresh(self) -> None:
        for s in self.shards:
            s.refresh()
        if self.replicas:
            t0 = time.perf_counter()
            for rep in self.replicas.values():
                rep.sync()
            if _iobs.enabled():
                _iobs.record_replica_sync(
                    len(self.replicas), (time.perf_counter() - t0) * 1000.0)
        self.generation += 1

    def flush(self) -> None:
        # persistence is IO-bound: fan shards out on the write pool when the
        # node provides one (reference ThreadPool.Names.FLUSH)
        if self.thread_pools is not None and len(self.shards) > 1:
            self.thread_pools.run_blocking("write",
                                           [s.flush for s in self.shards])
        else:
            for s in self.shards:
                s.flush()
        self.generation += 1
        # remote-backed storage: mirror every shard's new commit (reference
        # RemoteStoreRefreshListener uploads after each refresh/commit).
        # An upload failure must NOT fail the LOCAL commit — the shard
        # keeps serving, the tracker records the failure and the lag, and
        # the next flush retries (reference marks the shard lagging)
        if self.remote is not None:
            for sid, eng in enumerate(self.shards):
                if eng.path:
                    try:
                        self.remote.upload_shard(eng.path, sid)
                    except Exception:   # noqa: BLE001
                        # failure + lag recorded by the tracker; also
                        # counted into the write-path failure family
                        _iobs.count("indexing.flush.remote_failed")
            try:
                self.remote.upload_index_meta({
                    "settings": self.meta.settings,
                    "mappings": self.mappings.to_dict(),
                    "state": self.meta.state})
            except Exception:           # noqa: BLE001
                # counted by upload_index_meta itself, mirrored here so
                # `indexing.flush.remote_failed` covers every swallow
                _iobs.count("indexing.flush.remote_failed")

    def force_merge(self, max_num_segments: int = 1) -> None:
        for s in self.shards:
            s.force_merge(max_num_segments)
        # merged segments replace the shared objects; replicas must adopt
        # them or deletes against the merged set stay invisible on copies
        for rep in self.replicas.values():
            rep.sync()
        self.generation += 1

    @property
    def num_docs(self) -> int:
        return sum(s.num_docs for s in self.shards)

    def stats(self) -> dict:
        seg_count = sum(len(s.segments) for s in self.shards)
        store_bytes = 0
        for sh in self.shards:
            for seg in sh.segments:
                for pb in seg.postings.values():
                    store_bytes += pb.doc_ids.nbytes + pb.tfs.nbytes + pb.starts.nbytes
                for col in seg.numeric_cols.values():
                    store_bytes += col.values.nbytes
        ops = {k: sum(s.stats[k] for s in self.shards)
               for k in ("index_ops", "delete_ops", "refreshes", "flushes", "merges")}
        buf = [s.buffer_stats() for s in self.shards]
        # per-index refresh-to-visible percentiles: the accept→searchable
        # sketch this index's refreshes recorded ({} until the first one)
        rtv = METRICS.percentiles(
            f"indexing.index.{self.meta.name}.refresh_to_visible_ms")
        return {"docs": {"count": self.num_docs},
                "store": {"size_in_bytes": store_bytes},
                "slowlog": {"search": self.search_slowlog.stats(),
                            "indexing": self.index_slowlog.stats()},
                "segments": {"count": seg_count},
                "indexing": {"index_total": ops["index_ops"],
                             "delete_total": ops["delete_ops"],
                             "buffer": {
                                 "docs": sum(b["docs"] for b in buf),
                                 "bytes": sum(b["bytes"] for b in buf)}},
                "refresh": {"total": ops["refreshes"],
                            **({"refresh_to_visible_ms": rtv}
                               if rtv else {})},
                "flush": {"total": ops["flushes"]},
                "merges": {"total": ops["merges"],
                           "backlog": sum(s.merge_backlog()
                                          for s in self.shards)},
                **({"remote_store": self.remote.stats()}
                   if self.remote is not None else {})}

    def close(self) -> None:
        for s in self.shards:
            s.close()


class RequestCache:
    """Shard-request cache (reference IndicesRequestCache): response fragments
    keyed by (index, request-json, index generation); invalidated by writes
    via the generation."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max_entries
        self._store: Dict[tuple, dict] = {}
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[dict]:
        v = self._store.get(key)
        if v is None:
            self.misses += 1
        else:
            self.hits += 1
        return v

    def put(self, key: tuple, value: dict) -> None:
        if len(self._store) >= self.max_entries:
            try:
                # concurrent putters can race the same eviction victim
                # (32-thread closed loops hit this): the loser's pop must
                # not raise out of the search path
                self._store.pop(next(iter(self._store)), None)
            except (StopIteration, RuntimeError):
                pass  # store emptied/resized underfoot — nothing to evict
        self._store[key] = value

    def stats(self) -> dict:
        return {"hit_count": self.hits, "miss_count": self.misses,
                "entries": len(self._store)}


class Node:
    def __init__(self, data_path: Optional[str] = None,
                 cluster_name: str = "opensearch-tpu", node_name: str = "node-0",
                 mesh_service=None, remote_root: Optional[str] = None):
        self.metadata = ClusterMetadata(cluster_name)
        self.node_name = node_name
        self.data_path = data_path
        # remote-backed storage root (reference remote store repository):
        # when set, every flush mirrors shard commits to this blob root and
        # recovery can restore an index from the mirror alone
        self.remote_root = (remote_root
                            or os.environ.get("OPENSEARCH_TPU_REMOTE_ROOT")
                            or None)
        self.remote_stores: Dict[str, object] = {}
        self.indices: Dict[str, IndexService] = {}
        # cluster-metadata mutations (index create/delete/open/close,
        # template changes) serialize here — the single-master analog of
        # the reference's cluster-state update task queue
        self.meta_lock = threading.RLock()
        self.ingest = IngestService()
        from ..search.pipeline import SearchPipelineService
        self.search_pipelines = SearchPipelineService()
        self.breakers = BreakerService()
        self.request_cache = RequestCache()
        self.tasks = TaskRegistry()
        from ..utils.backpressure import SearchBackpressureService
        self.search_backpressure = SearchBackpressureService()
        self.thread_pools = ThreadPools()
        from ..utils.wlm import WorkloadManagement
        from .lifecycle import LifecycleService
        self.wlm = WorkloadManagement()
        self.lifecycle = LifecycleService(self)
        from ..utils.trace import TRACER
        self.tracer = TRACER
        # flight recorder (obs/flight_recorder.py): per-request black-box
        # event journal + anomaly dumps; process singleton like TRACER
        self.flight_recorder = _fr.RECORDER
        from .failure import FailureDetector
        self.failure_detector = FailureDetector(self)
        # node-level op counters (reference NodeIndicesStats rollup)
        self.op_counters = {"search_total": 0, "search_time_ms": 0.0,
                            "get_total": 0, "index_total": 0,
                            "index_time_ms": 0.0}
        # SPMD mesh dispatch (parallel/service.py): pass a MeshSearchService
        # the SPMD mesh path is ON BY DEFAULT whenever more than one device
        # is visible (a pod slice, or the virtual 8-CPU-device test mesh);
        # OPENSEARCH_TPU_MESH=0 disables it, =1 forces it even single-chip.
        # Eligible searches run the distributed program; everything else
        # falls back to the host shard loop with identical results.
        # mesh_service=False pins the TRUE host loop (parity-test
        # reference clients must not silently auto-enable a mesh)
        if mesh_service is False:
            mesh_service = None
        elif mesh_service is None:
            flag = os.environ.get("OPENSEARCH_TPU_MESH")
            enable = (flag not in (None, "", "0") if flag is not None
                      else self._device_count() > 1)
            if enable:
                from ..parallel.service import MeshSearchService
                mesh_service = MeshSearchService()
        self.mesh_service = mesh_service
        # cross-cluster search (reference RemoteClusterService): registered
        # peer Nodes searchable via "alias:index" expressions. Peers are
        # in-process, so CCS fans their shard searchers into THIS
        # coordinator's single reduce — full-fidelity aggs and unified DFS
        # stats across clusters (ccs_minimize_roundtrips=false model)
        self.remote_clusters: Dict[str, "Node"] = {}
        # HBM ledger (obs/hbm_ledger.py): the single source of truth for
        # device memory. Every residency tenant — fastpath aligned
        # postings, segment column pytrees, partial-residency arrays,
        # filter-specialized copies, nested sort columns — registers an
        # attributed allocation there, and the fielddata-breaker charge
        # is DERIVED from the registration (oslint OSL506: the ledger is
        # the sole charge path). Process singleton, matching the
        # one-device-per-process reality.
        from ..obs.hbm_ledger import LEDGER
        self.hbm_ledger = LEDGER
        LEDGER.set_breaker(self.breakers.breaker("fielddata"))
        # serving scheduler (serving/scheduler.py): coalesces concurrent
        # eligible searches into one batched device program invocation.
        # On by default whenever the mesh is attached; OPENSEARCH_TPU_SCHED
        # forces it on (single-chip kernel batching) or off
        from ..serving import ServingScheduler
        self.serving = ServingScheduler(self)
        # fleet observability (obs/timeseries.py + obs/slo.py): the
        # time-series retention ring behind `_nodes/stats/history` and
        # the SLO burn-rate engine behind `GET /_slo`. Process singletons
        # like METRICS/RECORDER/LEDGER; the sampler thread does NOT
        # auto-start (tests tick deterministically) unless
        # OPENSEARCH_TPU_TS=1 pins always-on retention for servers
        from ..obs.slo import SLO_ENGINE
        from ..obs.timeseries import SAMPLER
        self.timeseries = SAMPLER
        self.slo = SLO_ENGINE
        # query insights (obs/insights.py): workload fingerprinting +
        # heavy-hitter attribution at the search boundary — the input
        # the SLO-burn → remediation loop attributes blame with.
        # Process singleton like METRICS/RECORDER/SAMPLER.
        from ..obs.insights import INSIGHTS
        self.insights = INSIGHTS
        # remediation actuator (serving/remediator.py): the closed loop
        # from a firing slo.burn alert to bounded admission-level action
        # (shed offending shapes, tighten admission, deprioritize a sick
        # member). Process singleton, DISARMED by default — the serving
        # hot path pays one attribute read; OPENSEARCH_TPU_REMEDIATION=1
        # arms it against this node's SLO engine at init (servers), and
        # the traffic harness / tests arm injected instances explicitly
        from ..serving.remediator import REMEDIATOR
        self.remediation = REMEDIATOR
        if os.environ.get("OPENSEARCH_TPU_REMEDIATION") \
                not in (None, "", "0"):
            REMEDIATOR.arm(node=self)
        if os.environ.get("OPENSEARCH_TPU_TS") not in (None, "", "0"):
            SAMPLER.ensure_started()
        # persistent tasks (reference persistent/AllocatedPersistentTask):
        # durable task table + resumable executors; built-in: reindex
        from ..utils.persistent_tasks import PersistentTasksService
        self.persistent_tasks = PersistentTasksService(data_path,
                                                       self.thread_pools)
        self.persistent_tasks.register_executor("reindex",
                                                self._persistent_reindex)
        self.start_time = time.time()          # wall clock, display only
        self._start_mono = time.monotonic()    # durations (uptime)
        if data_path:
            os.makedirs(data_path, exist_ok=True)
            self._recover_indices()
            self._recover_data_streams()
            self.persistent_tasks.resume_all()

    @staticmethod
    def _device_count() -> int:
        import jax
        try:
            return len(jax.devices())
        except RuntimeError:
            return 1

    # ---------------- index lifecycle ----------------

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        with self.meta_lock:
            return self._create_index_locked(name, body)

    def _create_index_locked(self, name: str,
                             body: Optional[dict] = None) -> dict:
        if name in self.indices:
            raise ResourceAlreadyExistsError(f"index [{name}] already exists")
        body = body or {}
        settings = dict(body.get("settings", {}))
        mapping = body.get("mappings")
        # apply matching index templates (reference MetadataIndexTemplateService)
        for tmpl in reversed(self.metadata.matching_templates(name)):
            tbody = tmpl.get("template", tmpl)
            tsettings = tbody.get("settings", {})
            merged = dict(tsettings)
            merged.update(settings)
            settings = merged
            if mapping is None and tbody.get("mappings"):
                mapping = tbody["mappings"]
        meta = IndexMetadata(name, settings={"index": settings.get("index", settings)})
        svc = IndexService(meta, mapping, self.data_path,
                           thread_pools=self.thread_pools)
        self.indices[name] = svc
        self.metadata.indices[name] = meta
        self._attach_remote(name)
        for alias, acfg in body.get("aliases", {}).items():
            self._put_alias(alias, name, acfg)
        self.metadata.bump()
        self._persist_meta(name)
        return {"acknowledged": True, "shards_acknowledged": True, "index": name}

    def delete_index(self, expression: str, _ds_guard: bool = True) -> dict:
        from .datastream import (DataStreamError, guard_backing_delete,
                                 is_backing, release_deleted)
        if _ds_guard and expression in self.metadata.data_streams:
            # reference rejects index-API deletes of a data stream
            raise DataStreamError(
                f"[{expression}] is a data stream; use the data stream "
                f"delete API")
        names = self.metadata.resolve(expression, allow_no_indices=False)
        is_wild = "*" in str(expression) or "?" in str(expression)
        if _ds_guard:
            if is_wild:
                # wildcards skip (hidden) backing indices, like the
                # reference's expand-wildcards handling
                names = [n for n in names if is_backing(self, n) is None]
                if not names:
                    return {"acknowledged": True}
            else:
                for name in names:
                    guard_backing_delete(self, name)
        else:
            # guard-exempt path (ILM delete): never remove a write index
            for name in names:
                ds_name = is_backing(self, name)
                if ds_name is not None and \
                        self.metadata.data_streams[ds_name].write_index == name:
                    raise DataStreamError(
                        f"cannot delete the write index [{name}] of data "
                        f"stream [{ds_name}]")
        for name in names:
            with self.meta_lock:
                svc = self.indices.pop(name, None)
                self.metadata.indices.pop(name, None)
                for am in self.metadata.aliases.values():
                    am.indices.pop(name, None)
            if svc:
                # drain in-flight writers before tearing the engine down
                with svc.write_lock:
                    svc.close()
            if self.data_path:
                p = os.path.join(self.data_path, name)
                if os.path.exists(p):
                    shutil.rmtree(p)
            # a deleted index must not resurrect from the remote mirror on
            # the next restart, and a re-created index must not inherit a
            # stale mirror generation
            self.remote_stores.pop(name, None)
            if self.remote_root:
                rp = os.path.join(self.remote_root, name)
                if os.path.exists(rp):
                    shutil.rmtree(rp, ignore_errors=True)
        self.metadata.aliases = {a: am for a, am in self.metadata.aliases.items()
                                 if am.indices}
        if not _ds_guard:
            release_deleted(self, names)
        self.metadata.bump()
        return {"acknowledged": True}

    def get_index(self, name: str) -> IndexService:
        if name not in self.indices:
            raise IndexNotFoundError(f"no such index [{name}]")
        return self.indices[name]

    def index_service_for_write(self, name: str, auto_create: bool = True) -> IndexService:
        try:
            concrete = self.metadata.write_index(name)
        except IndexNotFoundError:
            if not auto_create:
                raise
            with self.meta_lock:
                # re-check under the lock: another writer (or an alias/
                # data-stream creation) may have claimed the name while
                # we waited — re-resolve rather than assume the concrete
                # index equals the request name
                try:
                    concrete = self.metadata.write_index(name)
                except IndexNotFoundError:
                    self._create_index_locked(name)
                    concrete = self.metadata.write_index(name)
        svc = self.indices[concrete]
        if svc.meta.state == "close":
            from .admin import IndexClosedError
            raise IndexClosedError(f"closed index [{concrete}]")
        return svc

    # ---------------- aliases ----------------

    def _put_alias(self, alias: str, index: str, cfg: Optional[dict] = None) -> None:
        am = self.metadata.aliases.setdefault(alias, AliasMetadata(alias))
        am.indices[index] = cfg or {}

    def update_aliases(self, actions: List[dict]) -> dict:
        for action in actions:
            ((verb, spec),) = action.items()
            indices = spec.get("indices", [spec.get("index")])
            aliases = spec.get("aliases", [spec.get("alias")])
            for idx in indices:
                for name in self.metadata.resolve(idx, allow_no_indices=False):
                    for al in aliases:
                        if verb == "add":
                            cfg = {k: v for k, v in spec.items()
                                   if k in ("filter", "is_write_index", "routing")}
                            self._put_alias(al, name, cfg)
                        elif verb == "remove":
                            am = self.metadata.aliases.get(al)
                            if am:
                                am.indices.pop(name, None)
                        else:
                            raise ClusterStateError(f"unknown alias action [{verb}]")
        self.metadata.aliases = {a: am for a, am in self.metadata.aliases.items()
                                 if am.indices}
        self.metadata.bump()
        return {"acknowledged": True}

    # ---------------- persistence / recovery ----------------

    def _persist_meta(self, name: str) -> None:
        if not self.data_path:
            return
        import json
        svc = self.indices[name]
        p = os.path.join(self.data_path, name)
        os.makedirs(p, exist_ok=True)
        with open(os.path.join(p, "index_meta.json"), "w") as fh:
            json.dump({"settings": svc.meta.settings,
                       "mappings": svc.mappings.to_dict(),
                       "state": svc.meta.state}, fh)

    # -------- index admin (cluster/admin.py; reference transport actions
    # under action/admin/indices/{settings,close,open,shrink}) --------

    def update_index_settings(self, expression: str, body: dict,
                              preserve_existing: bool = False) -> dict:
        from . import admin
        return admin.update_index_settings(self, expression, body,
                                           preserve_existing)

    def close_index(self, expression: str) -> dict:
        from . import admin
        return admin.close_index(self, expression)

    def open_index(self, expression: str) -> dict:
        from . import admin
        return admin.open_index(self, expression)

    def resize_index(self, source: str, target: str, kind: str,
                     body: Optional[dict] = None) -> dict:
        from . import admin
        return admin.resize_index(self, source, target, kind, body)

    def update_cluster_settings(self, body: dict) -> dict:
        from . import admin
        return admin.update_cluster_settings(self, body)

    def get_cluster_settings(self) -> dict:
        from . import admin
        return admin.get_cluster_settings(self)

    # -------- data streams (cluster/datastream.py) --------

    def _persist_data_streams(self) -> None:
        if not self.data_path:
            return
        import json
        with open(os.path.join(self.data_path, "data_streams.json"),
                  "w") as fh:
            json.dump({n: {"generation": ds.generation,
                           "indices": ds.indices}
                       for n, ds in self.metadata.data_streams.items()}, fh)

    def _recover_data_streams(self) -> None:
        import json

        from .datastream import DataStreamMetadata
        p = os.path.join(self.data_path, "data_streams.json")
        if not os.path.exists(p):
            return
        with open(p) as fh:
            saved = json.load(fh)
        for name, d in saved.items():
            indices = [i for i in d["indices"] if i in self.indices]
            if not indices:
                continue     # every backing index lost: the stream is gone
            self.metadata.data_streams[name] = DataStreamMetadata(
                name=name, generation=d["generation"], indices=indices)

    def resolve_open(self, expression, allow_no_indices: bool = True):
        """resolve() then drop closed indices from wildcard expansions;
        explicitly named closed indices raise IndexClosedError."""
        from . import admin
        names = self.metadata.resolve(expression, allow_no_indices)
        return admin.check_open(self, names, expression)

    def _reopen_service(self, name: str) -> None:
        """Re-apply statically-configurable settings after _open (analysis
        chain, default similarity) without rebuilding the engines."""
        from ..analysis import AnalysisRegistry
        svc = self.indices[name]
        idx = svc.meta.settings.get("index", {})
        svc.mappings.analysis = AnalysisRegistry(
            idx.get("analysis", svc.meta.settings.get("analysis")))
        # re-register programmatic chains (search_as_you_type shingle/prefix
        # analyzers live in the registry, not the user's settings)
        for ft in svc.mappings.fields.values():
            if ft.type == "search_as_you_type":
                shingles = sum(1 for s in ft.subfields if s.endswith("gram"))
                svc.mappings.analysis.ensure_sayt_chains(shingles + 1)
        sim = idx.get("similarity", svc.meta.settings.get("similarity", {}))
        svc.default_sim = (sim.get("default")
                           if isinstance(sim, dict) else None)
        for s in svc.searchers:
            s.similarity = svc.default_sim
        svc.generation += 1
        self._persist_meta(name)

    def _recover_indices(self) -> None:
        import json
        for name in sorted(os.listdir(self.data_path)):
            meta_path = os.path.join(self.data_path, name, "index_meta.json")
            if not os.path.exists(meta_path):
                continue
            with open(meta_path) as fh:
                saved = json.load(fh)
            meta = IndexMetadata(name, settings=saved.get("settings", {}))
            meta.state = saved.get("state", "open")
            svc = IndexService(meta, saved.get("mappings"), self.data_path,
                               thread_pools=self.thread_pools)
            self.indices[name] = svc
            self.metadata.indices[name] = meta
            self._attach_remote(name)
        # remote-backed indices absent locally (lost data dir, fresh node):
        # restore from the mirror alone — the headline remote-store promise
        # (reference RestoreRemoteStoreAction)
        from ..index.remote import remote_indices
        for name in remote_indices(self.remote_root):
            if name not in self.indices:
                self.restore_from_remote(name)

    # -------- remote-backed storage (index/remote.py) --------

    def _attach_remote(self, name: str) -> None:
        """Give an index its remote mirror when the node has a remote root
        and the index doesn't opt out (index.remote_store.enabled=false)."""
        if not self.remote_root:
            return
        svc = self.indices[name]
        rs_cfg = svc.meta.settings.get("index", {}).get("remote_store", {})
        if isinstance(rs_cfg, dict) and str(rs_cfg.get("enabled", True)) \
                in ("False", "false", "0"):
            return
        from ..index.remote import RemoteSegmentStore
        store = self.remote_stores.get(name)
        if store is None:
            store = RemoteSegmentStore(self.remote_root, name)
            self.remote_stores[name] = store
        svc.remote = store

    def restore_from_remote(self, name: str) -> dict:
        """Materialize an index from its remote mirror: download the latest
        generation of every shard into the local data dir, then recover the
        engines from the restored commit points + segments."""
        from ..index.remote import RemoteSegmentStore
        if not self.remote_root:
            raise ClusterStateError("no remote store root configured")
        if name in self.indices:
            raise ResourceAlreadyExistsError(
                f"index [{name}] exists; close and delete it before a "
                f"remote restore")
        if not self.data_path:
            raise ClusterStateError("remote restore requires a node data_path")
        store = RemoteSegmentStore(self.remote_root, name)
        saved = store.load_index_meta()
        if saved is None:
            raise IndexNotFoundError(f"no remote index [{name}]")
        restored_files = 0
        for sid in store.shard_ids():
            dest = os.path.join(self.data_path, name, str(sid))
            restored_files += store.restore_shard(sid, dest)
        meta = IndexMetadata(name, settings=saved.get("settings", {}))
        meta.state = saved.get("state", "open")
        svc = IndexService(meta, saved.get("mappings"), self.data_path,
                           thread_pools=self.thread_pools)
        self.indices[name] = svc
        self.metadata.indices[name] = meta
        self.remote_stores[name] = store
        svc.remote = store
        self._persist_meta(name)
        self.metadata.bump()
        return {"index": name, "restored_files": restored_files,
                "shards": len(store.shard_ids())}

    # -------- persistent-task executors (persistent/ reference) --------

    def _persistent_reindex(self, params: dict, progress: dict,
                            checkpoint) -> dict:
        """Resumable reindex: copies live docs of `source` into `dest` in
        _id order, checkpointing the done-count per batch — a restart
        resumes from the last checkpoint instead of starting over
        (reference reindex runs as a persistent task for exactly this)."""
        src = params["source"]
        dest = params["dest"]
        batch = int(params.get("batch", 500))
        if src not in self.indices:
            raise IndexNotFoundError(f"no such index [{src}]")
        svc = self.indices[src]
        # collect (id, segment ref, local) ONLY — sources are fetched per
        # batch at write time, so memory stays O(ids), not O(corpus)
        # (the reference streams scroll batches for the same reason)
        refs = []
        for sh in svc.shards:
            for seg in sh.segments:
                for local, did in enumerate(seg.ids):
                    if seg.live[local]:
                        refs.append((did, seg, local))
        refs.sort(key=lambda t: t[0])
        done = int(progress.get("docs", 0))
        dsvc = self.index_service_for_write(dest)
        while done < len(refs):
            for did, seg, local in refs[done: done + batch]:
                dsvc.route(did, None).index_doc(did,
                                                dict(seg.sources[local]))
            done = min(done + batch, len(refs))
            checkpoint({"docs": done, "total": len(refs)})
        dsvc.refresh()
        dsvc.generation += 1
        return {"docs": done, "total": len(refs)}

    # ---------------- snapshots (reference snapshots/SnapshotsService +
    # repositories/blobstore/BlobStoreRepository.java: incremental shard
    # snapshots with per-file dedup) ----------------

    def snapshot(self, repo_path: str, snapshot_name: str,
                 indices: str = "_all") -> dict:
        """Incremental, content-addressed snapshot: every file is stored
        once per repository under blobs/<md5>; a snapshot is a manifest
        mapping file paths to blob digests. Repeat snapshots of unchanged
        indices copy ZERO segment bytes (segments are immutable), exactly
        the reference's incremental shard-snapshot behavior."""
        import json

        from ..index.remote import _md5
        names = self.metadata.resolve(indices)
        snaps_dir = os.path.join(repo_path, "snapshots")
        blob_dir = os.path.join(repo_path, "blobs")
        man_path = os.path.join(snaps_dir, f"{snapshot_name}.json")
        if os.path.exists(man_path) or \
                os.path.exists(os.path.join(repo_path, snapshot_name)):
            raise ResourceAlreadyExistsError(
                f"snapshot [{snapshot_name}] already exists")
        if not self.data_path:
            raise ClusterStateError("snapshots require a node data_path")
        os.makedirs(snaps_dir, exist_ok=True)
        os.makedirs(blob_dir, exist_ok=True)
        files: Dict[str, dict] = {}
        new_bytes = 0
        shared_bytes = 0
        for name in names:
            svc = self.indices[name]
            svc.flush()
            root = os.path.join(self.data_path, name)
            for dirpath, _dirs, fnames in os.walk(root):
                for fn in fnames:
                    full = os.path.join(dirpath, fn)
                    rel = os.path.join(name, os.path.relpath(full, root))
                    digest = _md5(full)
                    size = os.path.getsize(full)
                    files[rel] = {"md5": digest, "size": size}
                    blob = os.path.join(blob_dir, digest)
                    if os.path.exists(blob):
                        shared_bytes += size      # dedup hit (incremental)
                    else:
                        # atomic blob write: a crash mid-copy must never
                        # leave a truncated file at the content address —
                        # every later snapshot would dedup against it
                        shutil.copy2(full, blob + ".tmp")
                        os.replace(blob + ".tmp", blob)
                        new_bytes += size
        manifest = {"snapshot": snapshot_name, "indices": names,
                    "files": files, "ts": time.time(), "state": "SUCCESS",
                    "stats": {"new_bytes": new_bytes,
                              "shared_bytes": shared_bytes,
                              "file_count": len(files)}}
        tmp = man_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, man_path)
        return {"snapshot": {"snapshot": snapshot_name, "indices": names,
                             "state": "SUCCESS",
                             "stats": manifest["stats"]}}

    def _load_snapshot_manifest(self, repo_path: str, snapshot_name: str):
        import json
        man_path = os.path.join(repo_path, "snapshots",
                                f"{snapshot_name}.json")
        if os.path.exists(man_path):
            with open(man_path) as fh:
                return json.load(fh)
        # legacy layout (pre-r4): <repo>/<name>/manifest.json + per-index
        # directory copies — still restorable
        legacy = os.path.join(repo_path, snapshot_name, "manifest.json")
        if os.path.exists(legacy):
            with open(legacy) as fh:
                m = json.load(fh)
            m["_legacy_dir"] = os.path.join(repo_path, snapshot_name)
            return m
        raise IndexNotFoundError(f"no such snapshot [{snapshot_name}]")

    def restore(self, repo_path: str, snapshot_name: str,
                rename_pattern: Optional[str] = None,
                rename_replacement: Optional[str] = None) -> dict:
        import json
        import re as _re
        manifest = self._load_snapshot_manifest(repo_path, snapshot_name)
        blob_dir = os.path.join(repo_path, "blobs")
        restored = []
        for name in manifest["indices"]:
            target = name
            if rename_pattern:
                target = _re.sub(rename_pattern, rename_replacement or "", name)
            if target in self.indices:
                raise ResourceAlreadyExistsError(
                    f"cannot restore index [{target}]: already exists")
            if "_legacy_dir" in manifest:
                shutil.copytree(os.path.join(manifest["_legacy_dir"], name),
                                os.path.join(self.data_path, target))
            else:
                prefix = name + os.sep
                for rel, meta in manifest["files"].items():
                    if not rel.startswith(prefix):
                        continue
                    dst = os.path.join(self.data_path, target,
                                       rel[len(prefix):])
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(os.path.join(blob_dir, meta["md5"]), dst)
            # translog/commit are part of the restored state; recover
            # normally
            meta_path = os.path.join(self.data_path, target, "index_meta.json")
            with open(meta_path) as fh:
                saved = json.load(fh)
            meta = IndexMetadata(target, settings=saved.get("settings", {}))
            self.indices[target] = IndexService(meta, saved.get("mappings"),
                                                self.data_path,
                                                thread_pools=self.thread_pools)
            self.metadata.indices[target] = meta
            self._attach_remote(target)
            restored.append(target)
        self.metadata.bump()
        return {"snapshot": {"snapshot": snapshot_name, "indices": restored,
                             "shards": {"failed": 0}}}

    # ---------------- search entry ----------------

    def _split_remote_expression(self, expression):
        """"logs,west:logs-*" -> (local names, [(alias, node, names)]).
        Reference RemoteClusterAware.groupClusterIndices."""
        local_parts: List[str] = []
        remote: List[tuple] = []
        parts = (expression if isinstance(expression, list)
                 else str(expression if expression is not None
                          else "").split(","))
        for part in parts:
            part = str(part).strip()
            alias = part.split(":", 1)[0] if ":" in part else None
            if alias is not None and alias in self.remote_clusters:
                rnode = self.remote_clusters[alias]
                sub = part.split(":", 1)[1]
                remote.append((alias, rnode, rnode.metadata.resolve(sub)))
            else:
                local_parts.append(part)
        # "" resolves to _all — only resolve locally when a local part
        # exists, else a pure-remote expression would sweep in every
        # local index
        names = (self.metadata.resolve(",".join(local_parts))
                 if local_parts and any(local_parts) else
                 (self.metadata.resolve(expression) if not remote else []))
        return names, remote

    def search(self, expression: str, body: dict, phase_hook=None,
               phase_ctx: Optional[dict] = None,
               copy_protect: bool = False,
               wlm_lane: Optional[str] = None,
               sli_lane: Optional[str] = None) -> dict:
        """`copy_protect`: caller intends to mutate the response (search
        pipeline response processors) — deep-copy it iff it aliases a
        request-cache entry, so cached entries stay pristine without taxing
        uncached paths. `wlm_lane`: serving-scheduler priority lane from
        the request's workload group (REST layer resolves it).
        `sli_lane`: the lane the per-lane SLIs and query-insights
        fingerprinting record under — defaults to `wlm_lane`, and
        differs only when the remediation actuator DEMOTED the request
        (serving/remediator.py): deprioritization changes scheduling
        priority, never accounting, or a demoted-to-batch interactive
        burn would vanish from the interactive SLO it fired.

        Flight-recorder timeline ownership: the REST facade usually
        starts the request's timeline (rest.accept); when none is
        current — direct engine callers, tests — this entry point owns
        one for the duration of the search, so every downstream event
        (scheduler, mesh, fastpath ladder) lands on a journal."""
        # per-lane SLIs (docs/OBSERVABILITY.md "fleet"): every search
        # lands one requests/errors/rejected count and one latency sample
        # under its lane — the counters the time-series sampler windows
        # and the SLO burn-rate engine judges (obs/slo.py). Recorded at
        # THIS boundary so cache hits, scheduler 429s and host-loop
        # fallbacks all count exactly once.
        from ..obs import insights as _ins
        from ..utils.metrics import METRICS as _m
        from ..utils.wlm import PressureRejectedException as _rej
        lane = sli_lane or wlm_lane or "interactive"
        _t0 = time.monotonic()
        _rec = self.flight_recorder
        tl = _fr.current() if _rec.enabled else 0
        token = None
        if _rec.enabled and not tl:
            tl = _rec.start("search", index=expression,
                            node=self.node_name)
            token = _fr.set_current(tl)
        # query insights (obs/insights.py): fingerprint the body at THIS
        # boundary — the same place the per-lane SLIs land — so cache
        # hits, rejections, errors and host-ladder attribution all
        # aggregate under one bounded query shape
        obs, ins_token = _ins.begin(body if isinstance(body, dict)
                                    else {}, lane)
        try:
            resp = self._search_recorded(expression, body, phase_hook,
                                         phase_ctx, copy_protect,
                                         wlm_lane, tl)
        except _rej:
            _m.counter(f"search.lane.{lane}.rejected").inc()
            _ins.finish(ins_token, obs, rejected=True, timeline_id=tl)
            raise
        except BaseException as e:
            # client-side 4xx API errors (bad query, missing index) are
            # the caller's fault, not lost availability — only server
            # faults burn the error budget
            if getattr(e, "status", 500) >= 500:
                _m.counter(f"search.lane.{lane}.errors").inc()
                _ins.finish(ins_token, obs, error=True, timeline_id=tl)
            else:
                _ins.finish(ins_token, obs, timeline_id=tl)
            raise
        finally:
            if token is not None:
                _fr.reset_current(token)
        _m.counter(f"search.lane.{lane}.requests").inc()
        took_ms = (time.monotonic() - _t0) * 1000.0
        if _m.enabled:
            _m.histogram(f"search.lane.{lane}.latency_ms").record(
                took_ms)
        _ins.finish(ins_token, obs, latency_ms=took_ms, timeline_id=tl)
        return resp

    def _search_recorded(self, expression: str, body: dict, phase_hook,
                         phase_ctx: Optional[dict], copy_protect: bool,
                         wlm_lane: Optional[str], tl: int) -> dict:
        # a body the mesh already declined in this request (msearch batch
        # decline -> per-body retry) skips the mesh: one logical search
        # counts at most one mesh fallback, and the retry does no wasted
        # eligibility work. Popped BEFORE cache-key derivation so the
        # marker never perturbs request-cache identity.
        mesh_declined = bool(body.pop("_mesh_declined", False)) \
            if isinstance(body, dict) else False
        names, remote_parts = self._split_remote_expression(expression)
        from .admin import check_open
        names = check_open(self, names, expression)
        searchers = []
        gens = []
        for name in names:
            svc = self.indices[name]
            searchers.extend(svc.search_copies())
            gens.append(svc.generation)
        for alias, rnode, rnames in remote_parts:
            for rn in rnames:
                rsvc = rnode.indices[rn]
                for sid in range(rsvc.meta.num_shards):
                    searchers.append(ShardSearcher(
                        rsvc.shards[sid], shard_id=sid,
                        similarity=rsvc.default_sim,
                        index_key=f"{alias}:{rn}"))
                gens.append((alias, rn, rsvc.generation))
        _rec = self.flight_recorder
        if _rec.enabled and tl:
            _rec.record(tl, "search.start", index=expression,
                        shards=len(searchers),
                        lane=wlm_lane or "interactive")
        # request cache (deterministic bodies only; a phase hook makes the
        # response depend on pipeline state, so it bypasses the cache)
        import json as _json
        try:
            cache_key = (tuple(names), _json.dumps(body, sort_keys=True), tuple(gens))
        except TypeError:
            cache_key = None
        if phase_hook is not None:
            cache_key = None
        if cache_key is not None:
            cached = self.request_cache.get(cache_key)
            if cached is not None:
                from ..obs import insights as _ins
                _ins.note_cache_hit()
                if _rec.enabled and tl:
                    _rec.record(tl, "cache.hit", index=expression)
                if copy_protect:
                    import copy as _copy
                    return _copy.deepcopy(cached)
                return cached
        # backpressure: hard admission gate, then duress check cancels the
        # worst in-flight offender (reference SearchBackpressureService)
        self.search_backpressure.admit(self.tasks)
        self.search_backpressure.check(self.tasks)
        task = self.tasks.register("indices:data/read/search",
                                   f"indices[{expression}]")
        task.timeline_id = tl      # _tasks <-> flight-recorder linkage
        t0 = time.monotonic()
        # ladder-rung attribution for the slowlog: which fastpath rungs
        # this request exercised. A STATS delta over the request window
        # (best-effort under concurrency — concurrent searches smear into
        # each other's windows; the trace span carries the exact story)
        from ..search import fastpath as _fp
        rungs_before = dict(_fp.STATS)
        root_span = None
        try:
            with self.tracer.span("indices:data/read/search",
                                  index=expression,
                                  shards=len(searchers)) as root_span:
                if _rec.enabled and tl and root_span is not None:
                    # key the timeline to the existing trace context, so
                    # journals and span trees cross-reference
                    _rec.annotate(tl, trace_root_id=root_span.span_id,
                                  task_id=task.id)
                resp = None
                if (len(names) == 1 and not remote_parts
                        and phase_hook is None
                        and self.indices[names[0]].mappings.star_trees):
                    # star-tree composite index: eligible size=0 agg
                    # requests answer from the pre-aggregated cubes
                    from ..search import startree
                    resp = startree.try_answer(
                        searchers, body,
                        self.indices[names[0]].mappings.star_trees)
                if (resp is None and not mesh_declined and len(names) == 1
                        and not remote_parts and phase_hook is None):
                    svc0 = self.indices[names[0]]
                    sched = self.serving
                    if sched is not None and sched.enabled:
                        # serving scheduler: coalesce this request with
                        # concurrent eligible ones into a single batched
                        # program invocation; non-coalescable shapes
                        # bypass unchanged
                        if sched.accepts(body):
                            resp = sched.execute(names[0], svc0, body,
                                                 task=task,
                                                 lane=wlm_lane
                                                 or "interactive")
                        else:
                            sched.note_bypass()
                            if self.mesh_service is not None:
                                resp = self.mesh_service.try_search(
                                    names[0], svc0, body)
                    elif self.mesh_service is not None:
                        resp = self.mesh_service.try_search(names[0], svc0,
                                                            body)
                    body.pop("_mesh_declined", None)
                if resp is None:
                    all_names = list(names) + [
                        f"{a}:{rn}" for a, _n, rns in remote_parts
                        for rn in rns]
                    # bit-consistency gate: when an SPMD mesh owns this
                    # node's hot path, OR replica read copies round-robin
                    # with the primary, a host-loop execution (decline,
                    # scheduler bypass, degradation, replica pick) must
                    # stay byte-identical to its XLA-domain siblings —
                    # the codec-v2 impact ladder serves the host-oracle
                    # f32 domain instead, so it only engages when this
                    # node's serving is single-domain
                    # (search/impactpath.py)
                    replicated = any(
                        getattr(self.indices[n], "replica_searchers",
                                None)
                        for n in names)
                    tok = impactpath.mesh_attached_token(
                        self.mesh_service is not None or replicated)
                    try:
                        resp = search_shards(searchers, body,
                                             index_name=",".join(all_names),
                                             task=task,
                                             phase_hook=phase_hook,
                                             phase_ctx=phase_ctx)
                    finally:
                        impactpath.reset_mesh_attached(tok)
        except BaseException as e:
            if _rec.enabled and tl:
                _rec.record(tl, "search.error", error=type(e).__name__)
            raise
        finally:
            self.tasks.unregister(task)
        took = time.monotonic() - t0

        def _slow_extra(_span=root_span, _before=rungs_before):
            # built only when a slowlog threshold fires: rung deltas say
            # WHICH escalation path burned the time, the root span says
            # WHERE inside the request it went; the insights fingerprint
            # says WHAT KIND of query this was (obs/insights.py — the
            # handle into `GET /_insights/top_queries`)
            from ..obs import insights as _ins
            rungs = {k: _fp.STATS[k] - _before.get(k, 0) for k in _before
                     if _fp.STATS[k] != _before.get(k, 0)}
            _obs = _ins.current()
            return {"fastpath_rungs": rungs,
                    "rescore_path": _fp.rescore_mode(),
                    **({"fingerprint": _obs.key} if _obs is not None
                       else {}),
                    **({"trace": _span.to_dict()}
                       if _span is not None else {})}

        self.op_counters["search_total"] += 1
        self.op_counters["search_time_ms"] += took * 1000.0
        if _rec.enabled and tl:
            _rec.record(tl, "search.done",
                        took_ms=round(took * 1000.0, 3),
                        hits=resp["hits"]["total"]["value"]
                        if isinstance(resp.get("hits", {}).get("total"),
                                      dict) else None)
        for name in names:
            # slowlog entries carry the timeline id, and a threshold hit
            # triggers a flight-recorder dump (utils/slowlog.py)
            self.indices[name].search_slowlog.maybe_log(
                took, body.get("query"), extra=_slow_extra,
                timeline_id=tl)
        if len(names) == 1 and not remote_parts:
            for h in resp["hits"]["hits"]:
                h["_index"] = names[0]
        if cache_key is not None and not resp.get("timed_out"):
            # a timed-out page is whatever the budget allowed at that
            # wall-clock moment — never representative, never cached
            self.request_cache.put(cache_key, resp)
            if copy_protect:
                import copy as _copy
                resp = _copy.deepcopy(resp)
        return resp

    def msearch(self, expression: str, bodies: List[dict]) -> Optional[List[dict]]:
        """Batched msearch over one index expression. Dispatch order: the
        SPMD mesh serves eligible bodies as ONE distributed program
        invocation per group (multi-shard indices on a pod); the remainder
        fuse into grouped Pallas kernel launches (grid over queries).
        Returns None when wholly ineligible — caller falls back per-body."""
        from .admin import check_open
        names = check_open(self, self.metadata.resolve(expression),
                           expression)
        searchers = []
        for name in names:
            searchers.extend(self.indices[name].searchers)
        resps: Optional[List[Optional[dict]]] = None
        if self.mesh_service is not None and len(names) == 1:
            # ALWAYS consult the mesh — including single-shard indices it
            # will decline: try_msearch attributes the decline
            # (fallback_shapes["single_shard"]) and marks the bodies
            # `_mesh_declined`, exactly like the direct per-request path,
            # so scheduler/msearch traffic and direct traffic report
            # identical mesh attribution (and the per-body retry derives
            # identical request-cache keys — the marker is popped before
            # key derivation)
            svc = self.indices[names[0]]
            resps = self.mesh_service.try_msearch(names[0], svc, bodies)
            if all(r is None for r in resps):
                resps = None
        if resps is None or any(r is None for r in resps):
            todo = ([i for i, r in enumerate(resps) if r is None]
                    if resps is not None else list(range(len(bodies))))
            batched = msearch_batched(searchers,
                                      [bodies[i] for i in todo],
                                      index_name=",".join(names))
            if batched is not None:
                if resps is None:
                    resps = [None] * len(bodies)
                for i, r in zip(todo, batched):
                    if resps[i] is None:
                        resps[i] = r
        if resps is not None and len(names) == 1:
            for resp in resps:
                if resp is None:
                    continue       # caller runs this body per-body
                for h in resp["hits"]["hits"]:
                    h["_index"] = names[0]
        return resps

    def stats(self) -> dict:
        out = {
            "cluster_name": self.metadata.cluster_name,
            "indices": {n: svc.stats() for n, svc in self.indices.items()},
            "breakers": self.breakers.stats(),
            "request_cache": self.request_cache.stats(),
            "tasks": self.tasks.stats(),
            "thread_pool": self.thread_pools.stats(),
            "search_pipelines": self.search_pipelines.stats(),
            "failure_detection": self.failure_detector.stats(),
            "wlm": self.wlm.stats(),
            "search_backpressure": self.search_backpressure.stats(),
            "persistent_tasks": self.persistent_tasks.stats(),
            "uptime_in_millis": int((time.monotonic() - self._start_mono)
                                    * 1000),
        }
        if self.mesh_service is not None:
            out["mesh"] = self.mesh_service.stats()
        return out
