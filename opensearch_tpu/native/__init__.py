"""ctypes loader + wrappers for the native host kernels (SURVEY §2.10).

Builds `_opensearch_native.so` from the adjacent C++ source with g++ on first
import (cached; rebuilt when the source is newer). Everything here has a
pure-Python/numpy fallback at its call sites — if the toolchain or the build
is unavailable, `available()` returns False and callers take the fallback.

Set ``OPENSEARCH_TPU_NATIVE=0`` to force the fallback paths (used by parity
tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "opensearch_native.cpp")
_SO = os.path.join(_HERE, "_opensearch_native.so")

_lib = None
_tried = False


def _build() -> bool:
    try:
        # build to a temp name + atomic rename so concurrent importers never
        # dlopen a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
        os.close(fd)
        res = subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
            capture_output=True, timeout=120)
        if res.returncode != 0:
            os.unlink(tmp)
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("OPENSEARCH_TPU_NATIVE", "1") == "0":
        return None
    try:
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign-arch artifact: rebuild from source and retry once
            if not _build():
                return None
            lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.osn_murmur3.restype = ctypes.c_uint32
    lib.osn_murmur3.argtypes = [u8p, ctypes.c_int64, ctypes.c_uint32]
    lib.osn_tokenize_ascii.restype = ctypes.c_int64
    lib.osn_tokenize_ascii.argtypes = [u8p, ctypes.c_int64, i32p,
                                       ctypes.c_int64]
    lib.osn_pack_new.restype = ctypes.c_void_p
    lib.osn_pack_new.argtypes = [ctypes.c_int32]
    lib.osn_pack_free.restype = None
    lib.osn_pack_free.argtypes = [ctypes.c_void_p]
    lib.osn_pack_add.restype = ctypes.c_int32
    lib.osn_pack_add.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int64,
                                 ctypes.c_int64, i32p, i32p]
    lib.osn_pack_finish.restype = ctypes.c_int32
    lib.osn_pack_finish.argtypes = [ctypes.c_void_p]
    lib.osn_pack_dims.restype = None
    lib.osn_pack_dims.argtypes = [ctypes.c_void_p, i64p]
    lib.osn_pack_export.restype = None
    lib.osn_pack_export.argtypes = [ctypes.c_void_p, i64p, i32p, f32p, i64p,
                                    i32p, u8p, i64p]
    lib.osn_maxscore_topk.restype = ctypes.c_int64
    lib.osn_maxscore_topk.argtypes = [i64p, i32p, f32p, f32p, f32p, f32p,
                                      i32p, ctypes.c_int32, ctypes.c_int32,
                                      ctypes.c_int32, u8p, i32p, f32p, i64p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def murmur3(data: bytes, seed: int = 0) -> int:
    lib = _load()
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, np.uint8)
    return int(lib.osn_murmur3(_u8(buf), len(data), seed & 0xFFFFFFFF))


def tokenize_ascii(text: str) -> np.ndarray:
    """(ntok, 2) int32 array of (start, end) offsets; ASCII input only."""
    lib = _load()
    raw = text.encode("ascii")
    buf = np.frombuffer(raw, dtype=np.uint8) if raw else np.zeros(1, np.uint8)
    cap = len(raw) // 2 + 1
    out = np.empty((cap, 2), dtype=np.int32)
    n = lib.osn_tokenize_ascii(_u8(buf), len(raw), _ptr(out, ctypes.c_int32),
                               cap)
    return out[:n]


class Packer:
    """Accumulate a token stream, emit the CSR postings layout of
    index/segment.py::build_segment. Tokens are passed as a single
    NUL-joined string per add() call (NULs inside a token are rejected with
    ValueError so the caller can fall back)."""

    def __init__(self, with_positions: bool):
        self._lib = _load()
        self._h = self._lib.osn_pack_new(1 if with_positions else 0)
        self.with_positions = with_positions

    def add(self, tokens_joined: str, ntok: int, doc_of: np.ndarray,
            positions: Optional[np.ndarray]) -> None:
        if ntok == 0:
            return
        raw = tokens_joined.encode("utf-8")
        buf = np.frombuffer(raw, dtype=np.uint8)
        doc_of = np.ascontiguousarray(doc_of, dtype=np.int32)
        posp = None
        if positions is not None:
            positions = np.ascontiguousarray(positions, dtype=np.int32)
            posp = _ptr(positions, ctypes.c_int32)
        rc = self._lib.osn_pack_add(self._h, _u8(buf), len(raw), ntok,
                                    _ptr(doc_of, ctypes.c_int32), posp)
        if rc != 0:
            raise ValueError("token stream contained embedded NUL")

    def finish(self):
        """-> (vocab: list[str], starts i64, doc_ids i32, tfs f32,
        pos_starts i64|None, positions i32|None)"""
        lib = self._lib
        lib.osn_pack_finish(self._h)
        dims = np.zeros(4, dtype=np.int64)
        lib.osn_pack_dims(self._h, _ptr(dims, ctypes.c_int64))
        nterms, npost, npos, vbytes = (int(x) for x in dims)
        starts = np.zeros(nterms + 1, dtype=np.int64)
        doc_ids = np.zeros(max(npost, 1), dtype=np.int32)
        tfs = np.zeros(max(npost, 1), dtype=np.float32)
        pos_starts = np.zeros(npost + 1, dtype=np.int64)
        positions = np.zeros(max(npos, 1), dtype=np.int32)
        vocab_buf = np.zeros(max(vbytes, 1), dtype=np.uint8)
        vocab_offs = np.zeros(nterms + 1, dtype=np.int64)
        lib.osn_pack_export(
            self._h, _ptr(starts, ctypes.c_int64),
            _ptr(doc_ids, ctypes.c_int32), _ptr(tfs, ctypes.c_float),
            _ptr(pos_starts, ctypes.c_int64), _ptr(positions, ctypes.c_int32),
            _u8(vocab_buf), _ptr(vocab_offs, ctypes.c_int64))
        raw = vocab_buf.tobytes()[:vbytes]
        vocab = [raw[vocab_offs[i]:vocab_offs[i + 1]].decode("utf-8")
                 for i in range(nterms)]
        if not self.with_positions:
            return vocab, starts, doc_ids[:npost], tfs[:npost], None, None
        return (vocab, starts, doc_ids[:npost], tfs[:npost], pos_starts,
                positions[:npos])

    def close(self) -> None:
        if self._h:
            self._lib.osn_pack_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def maxscore_topk(starts: np.ndarray, doc_ids: np.ndarray, tfs: np.ndarray,
                  kdoc: np.ndarray, idf: np.ndarray, ub: np.ndarray,
                  qterms: np.ndarray, msm: int, k: int,
                  filt: Optional[np.ndarray] = None):
    """Skipping (MaxScore/conjunction) BM25 top-k over one CSR field — the
    Lucene-BulkScorer-class CPU baseline used by bench.py, also a parity
    oracle for tests. qterms: i32[nt] term rows (-1 pad). msm: minimum
    matching terms (nt = conjunction). filt: optional u8[ndocs] 0/1 mask.
    -> (docs i32[k] (-1 pad), scores f32[k], total int — exact for the
    conjunction path, -1 when the MaxScore path early-terminated)."""
    lib = _load()
    if len(qterms) > 64:
        raise ValueError("maxscore_topk supports at most 64 query terms")
    starts = np.ascontiguousarray(starts, np.int64)
    doc_ids = np.ascontiguousarray(doc_ids, np.int32)
    tfs = np.ascontiguousarray(tfs, np.float32)
    kdoc = np.ascontiguousarray(kdoc, np.float32)
    idf = np.ascontiguousarray(idf, np.float32)
    ub = np.ascontiguousarray(ub, np.float32)
    qterms = np.ascontiguousarray(qterms, np.int32)
    fptr = None
    if filt is not None:
        filt = np.ascontiguousarray(filt, np.uint8)
        fptr = _u8(filt)
    k = min(k, 256)
    out_docs = np.empty(k, np.int32)
    out_scores = np.empty(k, np.float32)
    out_total = np.zeros(1, np.int64)
    lib.osn_maxscore_topk(
        _ptr(starts, ctypes.c_int64), _ptr(doc_ids, ctypes.c_int32),
        _ptr(tfs, ctypes.c_float), _ptr(kdoc, ctypes.c_float),
        _ptr(idf, ctypes.c_float), _ptr(ub, ctypes.c_float),
        _ptr(qterms, ctypes.c_int32), len(qterms), msm, k, fptr,
        _ptr(out_docs, ctypes.c_int32), _ptr(out_scores, ctypes.c_float),
        _ptr(out_total, ctypes.c_int64))
    return out_docs, out_scores, int(out_total[0])


def term_upper_bounds(starts: np.ndarray, doc_ids: np.ndarray,
                      tfs: np.ndarray, kdoc: np.ndarray,
                      idf: np.ndarray) -> np.ndarray:
    """Per-term MaxScore upper bounds idf_t * max_d tf/(tf+kdoc[d]),
    vectorized on host (one pass over the postings)."""
    contrib = tfs / (tfs + kdoc[doc_ids])
    nterms = len(starts) - 1
    ub = np.zeros(nterms, np.float32)
    nonempty = np.flatnonzero(np.diff(starts) > 0)
    if len(nonempty):
        maxes = np.maximum.reduceat(contrib, starts[nonempty])
        ub[nonempty] = maxes.astype(np.float32)
    return ub * idf[:nterms]
