// Native host-side kernels for opensearch_tpu (SURVEY §2.10).
//
// The reference (OpenSearch) runs on the JVM and leans on Lucene's
// MMap/VarHandle decode for its hot host loops; our host-side hot loops are
// (a) tokenization, (b) doc-id hashing for shard routing
// (`cluster/routing/Murmur3HashFunction.java` analog), and (c) packing
// buffered postings into the CSR segment layout at refresh time
// (the analog of Lucene's DWPT flush sort in
// `index/engine/InternalEngine.java#refresh`). The device never sees any of
// this — it consumes the CSR arrays this code produces.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// Python keeps a pure-numpy fallback for every entry point; parity is tested
// in tests/test_native.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <numeric>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// MurmurHash3 x86_32 — bit-exact with cluster/routing.py::murmur3_x86_32
// (which itself mirrors the reference's Murmur3HashFunction).
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t osn_murmur3(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
  uint32_t h = seed;
  const int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + i * 4, 4);  // little-endian host assumed (x86/arm)
    k *= c1;
    k = rotl32(k, 15);
    k *= c2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= tail[2] << 16; [[fallthrough]];
    case 2: k ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = rotl32(k, 15);
      k *= c2;
      h ^= k;
  }
  h ^= (uint32_t)len;
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

// ---------------------------------------------------------------------------
// ASCII standard tokenizer: byte-exact with the Python regex `[\w][\w']*`
// (analysis/tokenizers.py::standard_tokenizer) for pure-ASCII input. The
// Python wrapper only routes `text.isascii()` strings here, so the Unicode
// word classes never come into play.
// ---------------------------------------------------------------------------

static inline bool is_word(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

// Writes (start, end) byte-offset pairs into `out` (capacity `cap` pairs).
// Returns the number of tokens found (may exceed cap; caller re-sizes).
int64_t osn_tokenize_ascii(const uint8_t* buf, int64_t len, int32_t* out,
                           int64_t cap) {
  int64_t ntok = 0;
  int64_t i = 0;
  while (i < len) {
    if (is_word(buf[i])) {
      int64_t start = i++;
      while (i < len && (is_word(buf[i]) || buf[i] == '\'')) i++;
      if (ntok < cap) {
        out[2 * ntok] = (int32_t)start;
        out[2 * ntok + 1] = (int32_t)i;
      }
      ntok++;
    } else {
      i++;
    }
  }
  return ntok;
}

// ---------------------------------------------------------------------------
// CSR postings packer. Accumulates a token stream (term bytes, doc id,
// optional position) across calls, then `finish` sorts the vocabulary
// lexicographically (UTF-8 byte order == code-point order, matching Python's
// sorted()), remaps, sorts records by (term, doc, position), and emits the
// exact CSR layout produced by index/segment.py::build_segment.
// ---------------------------------------------------------------------------

struct Rec {
  int32_t tid, doc, pos;
};

struct Pack {
  bool with_pos;
  // term intern table; deque keeps element addresses stable for string_view
  std::deque<std::string> term_store;
  std::unordered_map<std::string_view, int32_t> lookup;
  std::vector<Rec> recs;
  // outputs
  std::vector<int64_t> starts;
  std::vector<int32_t> doc_ids;
  std::vector<float> tfs;
  std::vector<int64_t> pos_starts;
  std::vector<int32_t> positions;
  std::vector<int64_t> vocab_offs;
  std::string vocab_buf;
};

void* osn_pack_new(int32_t with_positions) {
  Pack* p = new Pack();
  p->with_pos = with_positions != 0;
  return p;
}

void osn_pack_free(void* h) { delete (Pack*)h; }

// `buf` holds `ntok` tokens separated by '\0' (no trailing separator);
// `doc_of[i]` is the doc for token i; `pos` is per-token position or null.
// Returns 0 on success, -1 if the separator count does not match ntok.
int32_t osn_pack_add(void* h, const uint8_t* buf, int64_t buflen, int64_t ntok,
                     const int32_t* doc_of, const int32_t* pos) {
  Pack* p = (Pack*)h;
  if (ntok == 0) return 0;
  const char* cur = (const char*)buf;
  const char* end = (const char*)buf + buflen;
  for (int64_t i = 0; i < ntok; i++) {
    const char* sep = (const char*)memchr(cur, '\0', end - cur);
    const char* tok_end = sep ? sep : end;
    if (!sep && i != ntok - 1) return -1;  // ran out of separators early
    std::string_view sv(cur, tok_end - cur);
    auto it = p->lookup.find(sv);
    int32_t tid;
    if (it == p->lookup.end()) {
      tid = (int32_t)p->term_store.size();
      p->term_store.emplace_back(sv);
      p->lookup.emplace(std::string_view(p->term_store.back()), tid);
    } else {
      tid = it->second;
    }
    p->recs.push_back({tid, doc_of[i], pos ? pos[i] : 0});
    cur = sep ? sep + 1 : end;
  }
  if (cur < end) return -1;  // extra separators: token had an embedded NUL
  return 0;
}

int32_t osn_pack_finish(void* h) {
  Pack* p = (Pack*)h;
  const int64_t nterms = (int64_t)p->term_store.size();
  // sort vocab lexicographically, build old->new tid map
  std::vector<int32_t> order(nterms);
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    return p->term_store[a] < p->term_store[b];
  });
  std::vector<int32_t> newtid(nterms);
  for (int64_t i = 0; i < nterms; i++) newtid[order[i]] = (int32_t)i;
  p->vocab_offs.assign(nterms + 1, 0);
  for (int64_t i = 0; i < nterms; i++) {
    p->vocab_buf += p->term_store[order[i]];
    p->vocab_offs[i + 1] = (int64_t)p->vocab_buf.size();
  }
  for (Rec& r : p->recs) r.tid = newtid[r.tid];
  std::sort(p->recs.begin(), p->recs.end(), [](const Rec& a, const Rec& b) {
    if (a.tid != b.tid) return a.tid < b.tid;
    if (a.doc != b.doc) return a.doc < b.doc;
    return a.pos < b.pos;
  });
  // scan: one posting per (tid, doc) group
  p->starts.assign(nterms + 1, 0);
  const int64_t nrec = (int64_t)p->recs.size();
  for (int64_t i = 0; i < nrec;) {
    int64_t j = i;
    while (j < nrec && p->recs[j].tid == p->recs[i].tid &&
           p->recs[j].doc == p->recs[i].doc)
      j++;
    p->doc_ids.push_back(p->recs[i].doc);
    p->tfs.push_back((float)(j - i));
    if (p->with_pos) {
      for (int64_t k = i; k < j; k++) p->positions.push_back(p->recs[k].pos);
      p->pos_starts.push_back((int64_t)p->positions.size());
    }
    p->starts[p->recs[i].tid + 1] = (int64_t)p->doc_ids.size();
    i = j;
  }
  // starts holds end offsets where a term had postings; fill gaps (terms can't
  // be absent here — every interned term has >=1 record — but keep it robust)
  for (int64_t t = 1; t <= nterms; t++)
    if (p->starts[t] < p->starts[t - 1]) p->starts[t] = p->starts[t - 1];
  return 0;
}

// dims out: [nterms, npostings, npositions, vocab_bytes]
void osn_pack_dims(void* h, int64_t* out) {
  Pack* p = (Pack*)h;
  out[0] = (int64_t)p->term_store.size();
  out[1] = (int64_t)p->doc_ids.size();
  out[2] = (int64_t)p->positions.size();
  out[3] = (int64_t)p->vocab_buf.size();
}

void osn_pack_export(void* h, int64_t* starts, int32_t* doc_ids, float* tfs,
                     int64_t* pos_starts, int32_t* positions, uint8_t* vocab,
                     int64_t* vocab_offs) {
  Pack* p = (Pack*)h;
  std::memcpy(starts, p->starts.data(), p->starts.size() * 8);
  if (!p->doc_ids.empty()) {
    std::memcpy(doc_ids, p->doc_ids.data(), p->doc_ids.size() * 4);
    std::memcpy(tfs, p->tfs.data(), p->tfs.size() * 4);
  }
  if (p->with_pos && pos_starts) {
    pos_starts[0] = 0;
    if (!p->pos_starts.empty())
      std::memcpy(pos_starts + 1, p->pos_starts.data(),
                  p->pos_starts.size() * 8);
    if (!p->positions.empty())
      std::memcpy(positions, p->positions.data(), p->positions.size() * 4);
  }
  if (!p->vocab_buf.empty()) std::memcpy(vocab, p->vocab_buf.data(), p->vocab_buf.size());
  std::memcpy(vocab_offs, p->vocab_offs.data(), p->vocab_offs.size() * 8);
}

// ---------------------------------------------------------------------------
// MaxScore / conjunction BM25 top-k over CSR postings — the bench's honest
// CPU baseline (the skipping scorer class Lucene runs: MaxScoreBulkScorer /
// ConjunctionDISI, reference `search/query/QueryPhase.java`). Document-at-a-
// time with per-term upper bounds, galloping cursor advance, and a strict-
// tie top-k heap (score desc, doc asc) identical to the device collector.
// ---------------------------------------------------------------------------

namespace {

struct HeapEnt {
  float score;
  int32_t doc;
};

// min-heap ordering: the WORST entry (lowest score, then largest doc) at root
static inline bool heap_worse(const HeapEnt& a, const HeapEnt& b) {
  return a.score < b.score || (a.score == b.score && a.doc > b.doc);
}

struct TopK {
  HeapEnt h[256];
  int n = 0, k;
  explicit TopK(int kk) : k(kk) {}
  bool full() const { return n == k; }
  float theta() const { return n == k ? h[0].score : -1e30f; }
  bool competitive(float s, int32_t d) const {
    if (n < k) return true;
    return s > h[0].score || (s == h[0].score && d < h[0].doc);
  }
  void sift_down(int i) {
    for (;;) {
      int l = 2 * i + 1, r = l + 1, m = i;
      if (l < n && heap_worse(h[l], h[m])) m = l;
      if (r < n && heap_worse(h[r], h[m])) m = r;
      if (m == i) return;
      std::swap(h[i], h[m]);
      i = m;
    }
  }
  void push(float s, int32_t d) {
    if (n < k) {
      h[n] = {s, d};
      int i = n++;
      while (i && heap_worse(h[i], h[(i - 1) / 2])) {
        std::swap(h[i], h[(i - 1) / 2]);
        i = (i - 1) / 2;
      }
    } else {
      h[0] = {s, d};
      sift_down(0);
    }
  }
  // fill out[0..k) score-desc, doc-asc; -1 pad
  void drain(int32_t* docs, float* scores) {
    std::sort(h, h + n, [](const HeapEnt& a, const HeapEnt& b) {
      return a.score > b.score || (a.score == b.score && a.doc < b.doc);
    });
    for (int i = 0; i < n; i++) {
      docs[i] = h[i].doc;
      scores[i] = h[i].score;
    }
    for (int i = n; i < k; i++) {
      docs[i] = -1;
      scores[i] = -1e30f;
    }
  }
};

// gallop `pos` forward until docs[pos] >= target (docs ascending)
static inline int64_t gallop(const int32_t* docs, int64_t pos, int64_t end,
                             int32_t target) {
  if (pos >= end || docs[pos] >= target) return pos;
  int64_t step = 1, lo = pos;
  while (pos + step < end && docs[pos + step] < target) {
    lo = pos + step;
    step <<= 1;
  }
  int64_t hi = std::min(pos + step, end);
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    if (docs[mid] < target) lo = mid + 1; else hi = mid;
  }
  return lo;
}

}  // namespace

// One query: `nt` term rows from the CSR, OR/msm or conjunction semantics,
// optional dense 0/1 filter. Returns number of hits written; totals[0] gets
// the exact hit count for the conjunction path, -1 for the early-terminating
// MaxScore path (Lucene likewise lower-bounds totals when it skips).
int64_t osn_maxscore_topk(const int64_t* starts, const int32_t* doc_ids,
                          const float* tfs, const float* kdoc,
                          const float* idf, const float* ub,
                          const int32_t* qterms, int32_t nt, int32_t msm,
                          int32_t k, const uint8_t* filter,
                          int32_t* out_docs, float* out_scores,
                          int64_t* out_total) {
  TopK top(k);
  // per-term state, dropping absent/empty rows
  int32_t tid[64];
  int64_t cur[64], end_[64];
  float tub[64];
  int T = 0;
  for (int i = 0; i < nt && i < 64; i++) {
    int32_t t = qterms[i];
    if (t < 0 || starts[t] == starts[t + 1]) continue;
    tid[T] = t;
    cur[T] = starts[t];
    end_[T] = starts[t + 1];
    tub[T] = ub[t];
    T++;
  }
  if (T == 0 || msm > T) {
    *out_total = 0;
    top.drain(out_docs, out_scores);
    return 0;
  }

  if (msm >= T) {
    // conjunction (ConjunctionDISI): drive on the rarest term, gallop rest
    int drv = 0;
    for (int i = 1; i < T; i++)
      if (end_[i] - cur[i] < end_[drv] - cur[drv]) drv = i;
    int64_t total = 0;
    for (int64_t p = cur[drv]; p < end_[drv]; p++) {
      int32_t d = doc_ids[p];
      if (filter && !filter[d]) continue;
      float s = idf[tid[drv]] * tfs[p] / (tfs[p] + kdoc[d]);
      bool all = true;
      for (int i = 0; i < T; i++) {
        if (i == drv) continue;
        cur[i] = gallop(doc_ids, cur[i], end_[i], d);
        if (cur[i] >= end_[i] || doc_ids[cur[i]] != d) {
          all = false;
          break;
        }
        s += idf[tid[i]] * tfs[cur[i]] / (tfs[cur[i]] + kdoc[d]);
      }
      if (!all) continue;
      total++;
      if (top.competitive(s, d)) top.push(s, d);
    }
    *out_total = total;
    int n = top.n;
    top.drain(out_docs, out_scores);
    return n;
  }

  // MaxScore OR: terms ascending by upper bound; prefix[i] = sum ub[0..i]
  int ord[64];
  for (int i = 0; i < T; i++) ord[i] = i;
  std::sort(ord, ord + T, [&](int a, int b) { return tub[a] < tub[b]; });
  float prefix[64];
  float acc = 0;
  for (int i = 0; i < T; i++) {
    acc += tub[ord[i]];
    prefix[i] = acc;
  }
  int ne = 0;  // terms ord[0..ne) are non-essential
  for (;;) {
    // next candidate: min current doc among essential terms
    int32_t d = INT32_MAX;
    for (int j = ne; j < T; j++) {
      int i = ord[j];
      if (cur[i] < end_[i] && doc_ids[cur[i]] < d) d = doc_ids[cur[i]];
    }
    if (d == INT32_MAX) break;
    float s = 0;
    int cnt = 0;
    for (int j = ne; j < T; j++) {
      int i = ord[j];
      if (cur[i] < end_[i] && doc_ids[cur[i]] == d) {
        s += idf[tid[i]] * tfs[cur[i]] / (tfs[cur[i]] + kdoc[d]);
        cnt++;
        cur[i]++;
      }
    }
    if (filter && !filter[d]) continue;
    float theta = top.theta();
    // try non-essential terms in descending bound order, pruning when even
    // their full upper bounds cannot reach the heap floor (strict: equal
    // score can still win on the doc-asc tie-break)
    for (int j = ne - 1; j >= 0; j--) {
      if (top.full() && s + prefix[j] < theta) break;
      int i = ord[j];
      cur[i] = gallop(doc_ids, cur[i], end_[i], d);
      if (cur[i] < end_[i] && doc_ids[cur[i]] == d) {
        s += idf[tid[i]] * tfs[cur[i]] / (tfs[cur[i]] + kdoc[d]);
        cnt++;
        cur[i]++;
      }
    }
    if (cnt >= msm && top.competitive(s, d)) {
      top.push(s, d);
      // grow the non-essential set as the heap floor rises
      float th = top.theta();
      if (top.full())
        while (ne < T - 1 && prefix[ne] < th) ne++;
    }
  }
  *out_total = -1;  // early-terminating scorer: exact totals not tracked
  int n = top.n;
  top.drain(out_docs, out_scores);
  return n;
}

}  // extern "C"
