// Native host-side kernels for opensearch_tpu (SURVEY §2.10).
//
// The reference (OpenSearch) runs on the JVM and leans on Lucene's
// MMap/VarHandle decode for its hot host loops; our host-side hot loops are
// (a) tokenization, (b) doc-id hashing for shard routing
// (`cluster/routing/Murmur3HashFunction.java` analog), and (c) packing
// buffered postings into the CSR segment layout at refresh time
// (the analog of Lucene's DWPT flush sort in
// `index/engine/InternalEngine.java#refresh`). The device never sees any of
// this — it consumes the CSR arrays this code produces.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// Python keeps a pure-numpy fallback for every entry point; parity is tested
// in tests/test_native.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <numeric>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// MurmurHash3 x86_32 — bit-exact with cluster/routing.py::murmur3_x86_32
// (which itself mirrors the reference's Murmur3HashFunction).
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t osn_murmur3(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
  uint32_t h = seed;
  const int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + i * 4, 4);  // little-endian host assumed (x86/arm)
    k *= c1;
    k = rotl32(k, 15);
    k *= c2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= tail[2] << 16; [[fallthrough]];
    case 2: k ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = rotl32(k, 15);
      k *= c2;
      h ^= k;
  }
  h ^= (uint32_t)len;
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

// ---------------------------------------------------------------------------
// ASCII standard tokenizer: byte-exact with the Python regex `[\w][\w']*`
// (analysis/tokenizers.py::standard_tokenizer) for pure-ASCII input. The
// Python wrapper only routes `text.isascii()` strings here, so the Unicode
// word classes never come into play.
// ---------------------------------------------------------------------------

static inline bool is_word(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

// Writes (start, end) byte-offset pairs into `out` (capacity `cap` pairs).
// Returns the number of tokens found (may exceed cap; caller re-sizes).
int64_t osn_tokenize_ascii(const uint8_t* buf, int64_t len, int32_t* out,
                           int64_t cap) {
  int64_t ntok = 0;
  int64_t i = 0;
  while (i < len) {
    if (is_word(buf[i])) {
      int64_t start = i++;
      while (i < len && (is_word(buf[i]) || buf[i] == '\'')) i++;
      if (ntok < cap) {
        out[2 * ntok] = (int32_t)start;
        out[2 * ntok + 1] = (int32_t)i;
      }
      ntok++;
    } else {
      i++;
    }
  }
  return ntok;
}

// ---------------------------------------------------------------------------
// CSR postings packer. Accumulates a token stream (term bytes, doc id,
// optional position) across calls, then `finish` sorts the vocabulary
// lexicographically (UTF-8 byte order == code-point order, matching Python's
// sorted()), remaps, sorts records by (term, doc, position), and emits the
// exact CSR layout produced by index/segment.py::build_segment.
// ---------------------------------------------------------------------------

struct Rec {
  int32_t tid, doc, pos;
};

struct Pack {
  bool with_pos;
  // term intern table; deque keeps element addresses stable for string_view
  std::deque<std::string> term_store;
  std::unordered_map<std::string_view, int32_t> lookup;
  std::vector<Rec> recs;
  // outputs
  std::vector<int64_t> starts;
  std::vector<int32_t> doc_ids;
  std::vector<float> tfs;
  std::vector<int64_t> pos_starts;
  std::vector<int32_t> positions;
  std::vector<int64_t> vocab_offs;
  std::string vocab_buf;
};

void* osn_pack_new(int32_t with_positions) {
  Pack* p = new Pack();
  p->with_pos = with_positions != 0;
  return p;
}

void osn_pack_free(void* h) { delete (Pack*)h; }

// `buf` holds `ntok` tokens separated by '\0' (no trailing separator);
// `doc_of[i]` is the doc for token i; `pos` is per-token position or null.
// Returns 0 on success, -1 if the separator count does not match ntok.
int32_t osn_pack_add(void* h, const uint8_t* buf, int64_t buflen, int64_t ntok,
                     const int32_t* doc_of, const int32_t* pos) {
  Pack* p = (Pack*)h;
  if (ntok == 0) return 0;
  const char* cur = (const char*)buf;
  const char* end = (const char*)buf + buflen;
  for (int64_t i = 0; i < ntok; i++) {
    const char* sep = (const char*)memchr(cur, '\0', end - cur);
    const char* tok_end = sep ? sep : end;
    if (!sep && i != ntok - 1) return -1;  // ran out of separators early
    std::string_view sv(cur, tok_end - cur);
    auto it = p->lookup.find(sv);
    int32_t tid;
    if (it == p->lookup.end()) {
      tid = (int32_t)p->term_store.size();
      p->term_store.emplace_back(sv);
      p->lookup.emplace(std::string_view(p->term_store.back()), tid);
    } else {
      tid = it->second;
    }
    p->recs.push_back({tid, doc_of[i], pos ? pos[i] : 0});
    cur = sep ? sep + 1 : end;
  }
  if (cur < end) return -1;  // extra separators: token had an embedded NUL
  return 0;
}

int32_t osn_pack_finish(void* h) {
  Pack* p = (Pack*)h;
  const int64_t nterms = (int64_t)p->term_store.size();
  // sort vocab lexicographically, build old->new tid map
  std::vector<int32_t> order(nterms);
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    return p->term_store[a] < p->term_store[b];
  });
  std::vector<int32_t> newtid(nterms);
  for (int64_t i = 0; i < nterms; i++) newtid[order[i]] = (int32_t)i;
  p->vocab_offs.assign(nterms + 1, 0);
  for (int64_t i = 0; i < nterms; i++) {
    p->vocab_buf += p->term_store[order[i]];
    p->vocab_offs[i + 1] = (int64_t)p->vocab_buf.size();
  }
  for (Rec& r : p->recs) r.tid = newtid[r.tid];
  std::sort(p->recs.begin(), p->recs.end(), [](const Rec& a, const Rec& b) {
    if (a.tid != b.tid) return a.tid < b.tid;
    if (a.doc != b.doc) return a.doc < b.doc;
    return a.pos < b.pos;
  });
  // scan: one posting per (tid, doc) group
  p->starts.assign(nterms + 1, 0);
  const int64_t nrec = (int64_t)p->recs.size();
  for (int64_t i = 0; i < nrec;) {
    int64_t j = i;
    while (j < nrec && p->recs[j].tid == p->recs[i].tid &&
           p->recs[j].doc == p->recs[i].doc)
      j++;
    p->doc_ids.push_back(p->recs[i].doc);
    p->tfs.push_back((float)(j - i));
    if (p->with_pos) {
      for (int64_t k = i; k < j; k++) p->positions.push_back(p->recs[k].pos);
      p->pos_starts.push_back((int64_t)p->positions.size());
    }
    p->starts[p->recs[i].tid + 1] = (int64_t)p->doc_ids.size();
    i = j;
  }
  // starts holds end offsets where a term had postings; fill gaps (terms can't
  // be absent here — every interned term has >=1 record — but keep it robust)
  for (int64_t t = 1; t <= nterms; t++)
    if (p->starts[t] < p->starts[t - 1]) p->starts[t] = p->starts[t - 1];
  return 0;
}

// dims out: [nterms, npostings, npositions, vocab_bytes]
void osn_pack_dims(void* h, int64_t* out) {
  Pack* p = (Pack*)h;
  out[0] = (int64_t)p->term_store.size();
  out[1] = (int64_t)p->doc_ids.size();
  out[2] = (int64_t)p->positions.size();
  out[3] = (int64_t)p->vocab_buf.size();
}

void osn_pack_export(void* h, int64_t* starts, int32_t* doc_ids, float* tfs,
                     int64_t* pos_starts, int32_t* positions, uint8_t* vocab,
                     int64_t* vocab_offs) {
  Pack* p = (Pack*)h;
  std::memcpy(starts, p->starts.data(), p->starts.size() * 8);
  if (!p->doc_ids.empty()) {
    std::memcpy(doc_ids, p->doc_ids.data(), p->doc_ids.size() * 4);
    std::memcpy(tfs, p->tfs.data(), p->tfs.size() * 4);
  }
  if (p->with_pos && pos_starts) {
    pos_starts[0] = 0;
    if (!p->pos_starts.empty())
      std::memcpy(pos_starts + 1, p->pos_starts.data(),
                  p->pos_starts.size() * 8);
    if (!p->positions.empty())
      std::memcpy(positions, p->positions.data(), p->positions.size() * 4);
  }
  if (!p->vocab_buf.empty()) std::memcpy(vocab, p->vocab_buf.data(), p->vocab_buf.size());
  std::memcpy(vocab_offs, p->vocab_offs.data(), p->vocab_offs.size() * 8);
}

}  // extern "C"
