from .pipeline import IngestService, Pipeline, IngestProcessorException

__all__ = ["IngestService", "Pipeline", "IngestProcessorException"]
