"""Content extraction for the attachment processor.

The reference plugin (plugins/ingest-attachment/.../AttachmentProcessor.java:1)
delegates to Apache Tika; this image has no Tika, so extraction is stdlib:

- plain text / UTF-8, UTF-16 (BOM-sniffed)
- HTML (html.parser; <title> -> title, body text -> content)
- RTF (control-word stripper)
- PDF (object-stream scan; FlateDecode via zlib; BT..ET Tj/TJ text ops)
- DOCX / XLSX / PPTX (zipfile + the OOXML part XML, tags stripped;
  docProps/core.xml -> title/author/keywords/date)

Output field contract matches the reference: content, content_type,
content_length, language, title, author, keywords, date (when present).
"""

from __future__ import annotations

import io
import re
import zipfile
import zlib


def _sniff(data: bytes) -> str:
    if data[:4] == b"%PDF":
        return "application/pdf"
    if data[:2] == b"PK":
        try:
            with zipfile.ZipFile(io.BytesIO(data)) as z:
                names = set(z.namelist())
            if "word/document.xml" in names:
                return ("application/vnd.openxmlformats-officedocument"
                        ".wordprocessingml.document")
            if "xl/workbook.xml" in names:
                return ("application/vnd.openxmlformats-officedocument"
                        ".spreadsheetml.sheet")
            if any(n.startswith("ppt/slides/") for n in names):
                return ("application/vnd.openxmlformats-officedocument"
                        ".presentationml.presentation")
            return "application/zip"
        except zipfile.BadZipFile:
            return "application/zip"
    if data[:5] == b"{\\rtf":
        return "application/rtf"
    head = data[:1024].lstrip().lower()
    if head.startswith((b"<!doctype html", b"<html")) or b"<html" in head:
        return "text/html"
    if head.startswith(b"<?xml"):
        return "application/xml"
    return "text/plain"


def _decode_text(data: bytes) -> str:
    for bom, enc in ((b"\xef\xbb\xbf", "utf-8"), (b"\xff\xfe", "utf-16-le"),
                     (b"\xfe\xff", "utf-16-be")):
        if data.startswith(bom):
            return data[len(bom):].decode(enc, "replace")
    try:
        return data.decode("utf-8")
    except UnicodeDecodeError:
        return data.decode("latin-1", "replace")


_TAG = re.compile(rb"<[^>]*>")
_TITLE = re.compile(rb"<title[^>]*>(.*?)</title>", re.S | re.I)
_SCRIPT = re.compile(rb"<(script|style)[^>]*>.*?</\1>", re.S | re.I)


def _extract_html(data: bytes) -> dict:
    import html
    out: dict = {}
    m = _TITLE.search(data)
    if m:
        out["title"] = html.unescape(_decode_text(m.group(1)).strip())
    body = _SCRIPT.sub(b" ", data)
    body = _TITLE.sub(b" ", body)
    text = html.unescape(_decode_text(_TAG.sub(b" ", body)))
    out["content"] = re.sub(r"\s+", " ", text).strip()
    return out


_RTF_CTRL = re.compile(r"\\[a-zA-Z]+-?\d* ?|\\[^a-zA-Z]|[{}]")
_RTF_UNI = re.compile(r"\\u(-?\d+) ?\??")


def _extract_rtf(data: bytes) -> dict:
    s = _decode_text(data)
    # drop embedded font/color/stylesheet groups before stripping controls
    s = re.sub(r"\{\\(?:fonttbl|colortbl|stylesheet|info|pict)[^{}]*"
               r"(?:\{[^{}]*\}[^{}]*)*\}", " ", s)
    s = _RTF_UNI.sub(lambda m: chr(int(m.group(1)) & 0xFFFF), s)
    s = s.replace("\\par", "\n").replace("\\tab", "\t")
    s = _RTF_CTRL.sub("", s)
    return {"content": re.sub(r"[ \t]+", " ", s).strip()}


# ---- PDF: scan indirect objects for content streams, inflate, read text ops
_PDF_STREAM = re.compile(rb"<<(.*?)>>\s*stream\r?\n", re.S)
_PDF_TJ = re.compile(rb"\((?:[^()\\]|\\.)*\)\s*Tj|\[(?:[^\[\]\\]|\\.)*?\]\s*TJ")
_PDF_STR = re.compile(rb"\((?:[^()\\]|\\.)*\)")
_PDF_ESC = {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b",
            b"f": b"\f", b"(": b"(", b")": b")", b"\\": b"\\"}


def _pdf_unescape(raw: bytes) -> bytes:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i:i + 1]
        if c == b"\\" and i + 1 < len(raw):
            nxt = raw[i + 1:i + 2]
            if nxt in _PDF_ESC:
                out += _PDF_ESC[nxt]
                i += 2
                continue
            if nxt.isdigit():          # octal escape
                oct_s = raw[i + 1:i + 4]
                j = 1
                while j <= 3 and raw[i + j:i + j + 1].isdigit():
                    j += 1
                out.append(int(oct_s[:j - 1], 8) & 0xFF)
                i += j
                continue
            i += 1
            continue
        out += c
        i += 1
    return bytes(out)


def _extract_pdf(data: bytes) -> dict:
    texts = []
    for m in _PDF_STREAM.finditer(data):
        hdr = m.group(1)
        start = m.end()
        end = data.find(b"endstream", start)
        if end < 0:
            continue
        raw = data[start:end].rstrip(b"\r\n")
        if b"FlateDecode" in hdr:
            try:
                raw = zlib.decompress(raw)
            except zlib.error:
                continue
        elif b"Filter" in hdr and b"FlateDecode" not in hdr:
            continue                   # unsupported codec (DCT, LZW, ...)
        if b"BT" not in raw:
            continue
        for op in _PDF_TJ.finditer(raw):
            for s in _PDF_STR.finditer(op.group(0)):
                piece = _pdf_unescape(s.group(0)[1:-1])
                try:
                    texts.append(piece.decode("utf-8"))
                except UnicodeDecodeError:
                    texts.append(piece.decode("latin-1", "replace"))
        texts.append("\n")
    out = {"content": re.sub(r"[ \t]+", " ", "".join(texts)).strip()}
    m = re.search(rb"/Title\s*\(((?:[^()\\]|\\.)*)\)", data)
    if m:
        out["title"] = _pdf_unescape(m.group(1)).decode("latin-1", "replace")
    m = re.search(rb"/Author\s*\(((?:[^()\\]|\\.)*)\)", data)
    if m:
        out["author"] = _pdf_unescape(m.group(1)).decode("latin-1", "replace")
    return out


_XML_TAG = re.compile(r"<[^>]*>")


def _ooxml_meta(z: zipfile.ZipFile, out: dict) -> None:
    try:
        core = z.read("docProps/core.xml").decode("utf-8", "replace")
    except KeyError:
        return
    for tag, key in (("dc:title", "title"), ("dc:creator", "author"),
                     ("cp:keywords", "keywords"),
                     ("dcterms:created", "date")):
        m = re.search(rf"<{tag}[^>]*>(.*?)</{tag}>", core, re.S)
        if m and m.group(1).strip():
            out[key] = m.group(1).strip()


def _extract_ooxml(data: bytes, kind: str) -> dict:
    out: dict = {}
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        names = z.namelist()
        parts: list = []
        if kind == "docx":
            parts = ["word/document.xml"]
        elif kind == "xlsx":
            parts = [n for n in ("xl/sharedStrings.xml",) if n in names]
        else:                          # pptx
            parts = sorted(n for n in names
                           if re.fullmatch(r"ppt/slides/slide\d+\.xml", n))
        texts = []
        for part in parts:
            try:
                xml = z.read(part).decode("utf-8", "replace")
            except KeyError:
                continue
            # OOXML runs: text lives in <w:t>/<t>/<a:t> elements; insert
            # spaces at paragraph/row boundaries so words don't concatenate
            xml = re.sub(r"</(?:w:p|row|a:p)>", "\n", xml)
            xml = re.sub(r"<(?:w:tab|w:br)[^>]*/>", "\t", xml)
            body = _XML_TAG.sub("", xml)
            import html as _h
            texts.append(_h.unescape(body))
        out["content"] = re.sub(r"[ \t]+", " ", "\n".join(texts)).strip()
        _ooxml_meta(z, out)
    return out


def extract(data: bytes, indexed_chars: int = 100_000) -> dict:
    ctype = _sniff(data)
    if ctype == "application/pdf":
        out = _extract_pdf(data)
    elif ctype == "text/html":
        out = _extract_html(data)
    elif ctype == "application/rtf":
        out = _extract_rtf(data)
    elif ctype.endswith("wordprocessingml.document"):
        out = _extract_ooxml(data, "docx")
    elif ctype.endswith("spreadsheetml.sheet"):
        out = _extract_ooxml(data, "xlsx")
    elif ctype.endswith("presentationml.presentation"):
        out = _extract_ooxml(data, "pptx")
    elif ctype in ("application/zip",):
        out = {"content": ""}
    else:
        out = {"content": _decode_text(data).strip()}
    content = out.get("content", "")
    if indexed_chars >= 0:
        content = content[:indexed_chars]
    out["content"] = content
    out["content_type"] = ctype
    out["content_length"] = len(content)
    if content:
        out["language"] = _guess_language(content)
    return out


_LANG_HINTS = (
    ("en", (" the ", " and ", " of ", " to ", " is ")),
    ("de", (" der ", " die ", " und ", " das ", " ist ")),
    ("fr", (" le ", " la ", " les ", " est ", " une ")),
    ("es", (" el ", " los ", " las ", " que ", " una ")),
)


def _guess_language(text: str) -> str:
    """Tiny stopword-vote language hint (Tika's detector is a full n-gram
    model; this covers the common cases the tests and docs exercise)."""
    sample = f" {text[:4000].lower()} "
    if re.search(r"[\u3040-\u30ff]", sample):
        return "ja"
    if re.search(r"[\uac00-\ud7af]", sample):
        return "ko"
    if re.search(r"[\u4e00-\u9fff]", sample):
        return "zh"
    if re.search(r"[\u0400-\u04ff]", sample):
        return "ru"
    best, best_n = "en", 0
    for lang, words in _LANG_HINTS:
        n = sum(sample.count(w) for w in words)
        if n > best_n:
            best, best_n = lang, n
    return best
