"""Long-tail ingest processors: the reference's `ingest-common` remainder
(dissect/kv/json/csv/bytes/urldecode/uri_parts/html_strip/fingerprint/sort/
dot_expander/foreach/date_index_name/community_id/remove_by_pattern) plus the
`ingest-user-agent` (modules/ingest-user-agent/.../UserAgentProcessor.java:1),
`ingest-geoip` (modules/ingest-geoip/.../GeoIpProcessor.java:1) and
`ingest-attachment` (plugins/ingest-attachment/.../AttachmentProcessor.java:1)
plugins.

Design notes vs the reference:
- user_agent ships the uap-core regex corpus with the plugin; the image has
  no such data file, so the parser here is a compact rule table covering the
  dominant browser/OS/device families, emitting the same ECS field shapes
  (`name`, `version`, `os.{name,version,full}`, `device.name`, `original`).
- geoip ships MaxMind GeoLite2; zero-egress image has no .mmdb, so the
  processor resolves against (a) an operator-supplied JSON database
  (`database_file` param: {"cidr": {fields...}}) and (b) a small built-in
  table of well-known public resolver/documentation ranges, enough to make
  the field contract and the miss/private-range semantics real.
- attachment swaps Tika for stdlib extractors (see attachment.py): plain
  text, HTML, RTF, PDF (FlateDecode via zlib), DOCX/XLSX (zipfile + XML).
"""

from __future__ import annotations

import base64
import binascii
import datetime as _dt
import fnmatch
import hashlib
import ipaddress
import json as _json
import re
import struct
import urllib.parse
from typing import Callable, List, Optional

from .pipeline import (IngestProcessorException, _del_path, _get_path,
                       _render, _set_path)


# ---------------------------------------------------------------- structure

def _p_json(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field")
    add_to_root = cfg.get("add_to_root", False)

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        try:
            parsed = _json.loads(v) if isinstance(v, (str, bytes)) else v
        except ValueError as e:
            raise IngestProcessorException(f"invalid json in [{field}]: {e}")
        if add_to_root:
            if not isinstance(parsed, dict):
                raise IngestProcessorException(
                    "cannot add non-map fields to root of document")
            doc.update(parsed)
        else:
            _set_path(doc, target or field, parsed)
    return p


def _p_kv(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    fs, vs = cfg["field_split"], cfg["value_split"]
    prefix = cfg.get("prefix", "")
    target = cfg.get("target_field")
    include = set(cfg.get("include_keys", []) or [])
    exclude = set(cfg.get("exclude_keys", []) or [])
    strip = cfg.get("trim_key", ""), cfg.get("trim_value", "")

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        for part in re.split(fs, str(v)):
            if not part:
                continue
            kv = re.split(vs, part, maxsplit=1)
            if len(kv) != 2:
                if cfg.get("strict", False):
                    raise IngestProcessorException(
                        f"field [{field}] does not contain value_split "
                        f"[{vs}]: [{part}]")
                continue
            k, val = kv[0].strip(strip[0] or None), kv[1].strip(strip[1] or None)
            if include and k not in include:
                continue
            if k in exclude:
                continue
            path = f"{target}.{prefix}{k}" if target else f"{prefix}{k}"
            _set_path(doc, path, val)
    return p


_DISSECT_KEY = re.compile(r"%\{([^}]*)\}")


def _compile_dissect(pattern: str):
    """-> list of (literal, key, mode, skip, right_pad) segments.

    Supported modifiers (reference DissectParser): `+key` append with the
    pattern's append_separator, `?key`/empty skip, `key->` right-padding
    (greedy trailing delimiter run), `*key`/`&key` reference pairs (`*`
    captures the output FIELD NAME, `&` the value; paired by key).
    mode is one of "" (plain), "+" (append), "*" (name), "&" (value).
    """
    segs = []
    last = 0
    for m in _DISSECT_KEY.finditer(pattern):
        lit = pattern[last:m.start()]
        key = m.group(1)
        mode = ""
        if key[:1] in ("+", "*", "&"):
            mode, key = key[0], key[1:]
        skip = key.startswith("?") or key == ""
        if key.startswith("?"):
            key = key[1:]
        pad = key.endswith("->")
        if pad:
            key = key[:-2]
        segs.append((lit, key, mode, skip, pad))
        last = m.end()
    return segs, pattern[last:]


def _p_dissect(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    segs, tail_lit = _compile_dissect(cfg["pattern"])
    app_sep = cfg.get("append_separator", "")

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        s = str(v)
        pos = 0
        out: dict = {}
        ref_names: dict = {}    # *key captures -> output field name
        ref_vals: dict = {}     # &key captures -> output field value
        for i, (lit, key, mode, skip, pad) in enumerate(segs):
            if lit:
                idx = s.find(lit, pos)
                if idx < 0:
                    raise IngestProcessorException(
                        f"dissect pattern does not match [{s}]")
                pos = idx + len(lit)
            nxt = segs[i + 1][0] if i + 1 < len(segs) else tail_lit
            if nxt:
                end = s.find(nxt, pos)
                if end < 0:
                    raise IngestProcessorException(
                        f"dissect pattern does not match [{s}]")
            else:
                end = len(s)
            val = s[pos:end]
            pos = end
            if pad:
                # key-> greedily swallows the trailing delimiter run
                while nxt and s[pos:pos + len(nxt)] == nxt:
                    pos += len(nxt)
            if skip:
                continue
            if mode == "*":
                ref_names[key] = val
            elif mode == "&":
                ref_vals[key] = val
            elif mode == "+" and key in out:
                out[key] = f"{out[key]}{app_sep}{val}"
            else:
                out[key] = val
        if tail_lit and not s.startswith(tail_lit, pos):
            raise IngestProcessorException(
                f"dissect pattern does not match [{s}]")
        for k, fname in ref_names.items():
            if k not in ref_vals:
                raise IngestProcessorException(
                    f"dissect reference key [*{k}] has no paired [&{k}]")
            out[fname] = ref_vals[k]
        for k, val in out.items():
            _set_path(doc, k, val)
    return p


def _p_csv(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    targets: List[str] = cfg["target_fields"]
    sep = cfg.get("separator", ",")
    quote = cfg.get("quote", '"')
    trim = cfg.get("trim", False)
    empty = cfg.get("empty_value", "")

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        import csv as _csv
        import io
        row = next(_csv.reader(io.StringIO(str(v)), delimiter=sep,
                               quotechar=quote or None), [])
        for i, t in enumerate(targets):
            val = row[i] if i < len(row) else empty
            if trim and isinstance(val, str):
                val = val.strip()
            _set_path(doc, t, val if val != "" else empty)
    return p


_BYTES_UNITS = {"b": 1, "kb": 1024, "mb": 1024 ** 2, "gb": 1024 ** 3,
                "tb": 1024 ** 4, "pb": 1024 ** 5}


def _p_bytes(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", field)

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*", str(v))
        unit = (m.group(2) if m else "").lower() or "b"
        if not m or unit not in _BYTES_UNITS:
            raise IngestProcessorException(
                f"failed to parse setting [{v}] as a size in bytes")
        _set_path(doc, target, int(float(m.group(1)) * _BYTES_UNITS[unit]))
    return p


def _p_urldecode(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", field)

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        _set_path(doc, target, urllib.parse.unquote_plus(str(v)))
    return p


def _p_uri_parts(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", "url")
    keep = cfg.get("keep_original", True)
    remove_if_successful = cfg.get("remove_if_successful", False)

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        try:
            u = urllib.parse.urlsplit(str(v))
            parts: dict = {"path": u.path}
            if u.scheme:
                parts["scheme"] = u.scheme
            if u.hostname:
                parts["domain"] = u.hostname
            if u.port:    # deferred validation: can raise on bad ports
                parts["port"] = u.port
            if u.query:
                parts["query"] = u.query
            if u.fragment:
                parts["fragment"] = u.fragment
            if u.username:
                parts["username"] = u.username
                parts["user_info"] = f"{u.username}:{u.password or ''}"
        except ValueError as e:
            raise IngestProcessorException(f"unable to parse URI [{v}]: {e}")
        if "." in u.path.rsplit("/", 1)[-1]:
            parts["extension"] = u.path.rsplit(".", 1)[-1]
        if keep:
            parts["original"] = str(v)
        _set_path(doc, target, parts)
        if remove_if_successful and field != target:
            _del_path(doc, field)
    return p


_TAG_RE = re.compile(r"<[^>]*>")


def _p_html_strip(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", field)

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        import html
        _set_path(doc, target, html.unescape(_TAG_RE.sub("", str(v))))
    return p


def _p_fingerprint(cfg: dict) -> Callable[[dict], None]:
    fields = sorted(cfg["fields"])
    target = cfg.get("target_field", "fingerprint")
    method = cfg.get("method", "SHA-1@2.16.0").split("@")[0].lower()
    algo = {"sha-1": "sha1", "sha-256": "sha256", "md5": "md5",
            "sha-512": "sha512"}.get(method)
    if algo is None:
        raise IngestProcessorException(
            f"unsupported fingerprint method [{method}]")

    def p(doc):
        h = hashlib.new(algo)
        seen = False
        for f in fields:
            v = _get_path(doc, f)
            if v is None:
                if cfg.get("ignore_missing"):
                    continue
                raise IngestProcessorException(f"field [{f}] not present")
            seen = True
            h.update(f.encode())
            h.update(b"|")
            h.update(_json.dumps(v, sort_keys=True, default=str).encode())
            h.update(b"|")
        if seen:
            _set_path(doc, target,
                      base64.b64encode(h.digest()).decode())
    return p


def _p_sort(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", field)
    reverse = cfg.get("order", "asc") == "desc"

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        if not isinstance(v, list):
            raise IngestProcessorException(
                f"field [{field}] is not a list and cannot be sorted")
        try:
            _set_path(doc, target, sorted(v, reverse=reverse))
        except TypeError as e:
            raise IngestProcessorException(
                f"cannot sort field [{field}]: {e}")
    return p


def _p_dot_expander(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    path = cfg.get("path")

    def p(doc):
        root = _get_path(doc, path) if path else doc
        if not isinstance(root, dict):
            return
        if field == "*":
            keys = [k for k in list(root) if "." in k]
        else:
            keys = [field] if field in root else []
        for k in keys:
            # conflict check BEFORE mutating: any ancestor along the dotted
            # path that exists as a non-dict blocks expansion
            node = root
            parts = k.split(".")
            for part in parts[:-1]:
                if part in node and not isinstance(node[part], dict):
                    raise IngestProcessorException(
                        f"cannot expand [{k}]: conflicts with existing "
                        f"field [{part}]")
                node = node.get(part, {})
            v = root.pop(k)
            leaf = _get_path(root, k)
            if leaf is None:
                _set_path(root, k, v)
            elif isinstance(leaf, list):
                leaf.extend(v if isinstance(v, list) else [v])
            else:      # existing leaf -> append into a list, as upstream
                _set_path(root, k,
                          [leaf] + (v if isinstance(v, list) else [v]))
    return p


def _p_remove_by_pattern(cfg: dict) -> Callable[[dict], None]:
    pats = cfg.get("field_pattern")
    pats = pats if isinstance(pats, list) else [pats]

    def p(doc):
        for k in [k for k in list(doc)
                  if any(fnmatch.fnmatch(k, pt) for pt in pats)]:
            doc.pop(k, None)
    return p


def _p_foreach(cfg: dict, service=None) -> Callable[[dict], None]:
    from .pipeline import build_processor
    field = cfg["field"]
    ((kind, sub_cfg),) = cfg["processor"].items()
    sub = build_processor(kind, sub_cfg, service)   # compile once

    def p(doc):
        vals = _get_path(doc, field)
        if vals is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        if not isinstance(vals, list):
            raise IngestProcessorException(
                f"field [{field}] is not a list, cannot loop over its items")
        # the element is exposed as _ingest._value on the REAL document
        # (reference ForEachProcessor): sub-processor writes to other
        # fields land in the doc; _ingest is restored afterwards
        saved_ingest = doc.get("_ingest")
        out = []
        try:
            for v in vals:
                doc["_ingest"] = {"_value": v}
                sub(doc)
                out.append(doc["_ingest"]["_value"])
        finally:
            if saved_ingest is None:
                doc.pop("_ingest", None)
            else:
                doc["_ingest"] = saved_ingest
        _set_path(doc, field, out)
    return p


def _p_date_index_name(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    rounding = cfg["date_rounding"]
    prefix = cfg.get("index_name_prefix", "")
    fmt = cfg.get("index_name_format", "yyyy-MM-dd")
    formats = cfg.get("date_formats", ["ISO8601"])
    # joda -> strftime for the common tokens
    py_fmt = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
              .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M"))

    def p(doc):
        v = _get_path(doc, field)
        d = None
        for f in formats:
            try:
                if f in ("ISO8601", "strict_date_optional_time"):
                    d = _dt.datetime.fromisoformat(
                        str(v).replace("Z", "+00:00"))
                elif f == "UNIX":
                    d = _dt.datetime.fromtimestamp(float(v), _dt.timezone.utc)
                elif f == "UNIX_MS":
                    d = _dt.datetime.fromtimestamp(float(v) / 1000,
                                                   _dt.timezone.utc)
                else:
                    d = _dt.datetime.strptime(str(v), f)
                break
            except (ValueError, TypeError):
                continue
        if d is None:
            raise IngestProcessorException(f"unable to parse date [{v}]")
        # truncate to the rounding unit, then format
        if rounding == "y":
            d = d.replace(month=1, day=1, hour=0, minute=0, second=0,
                          microsecond=0)
        elif rounding == "M":
            d = d.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        elif rounding == "w":
            d = (d - _dt.timedelta(days=d.weekday())).replace(
                hour=0, minute=0, second=0, microsecond=0)
        elif rounding == "d":
            d = d.replace(hour=0, minute=0, second=0, microsecond=0)
        elif rounding == "h":
            d = d.replace(minute=0, second=0, microsecond=0)
        elif rounding == "m":
            d = d.replace(second=0, microsecond=0)
        # the reference writes a date-math expression into _index; the bulk
        # path resolves it — here we resolve directly to the concrete name
        doc["_index"] = f"{_render(prefix, doc)}{d.strftime(py_fmt)}"
    return p


# ------------------------------------------------------------- community_id

_PROTO_NUM = {"icmp": 1, "igmp": 2, "tcp": 6, "udp": 17, "gre": 47,
              "icmp6": 58, "ipv6-icmp": 58, "sctp": 132}
# ICMP type -> the "reply" type used to order endpoints like a port pair
_ICMP_EQUIV = {8: 0, 0: 8, 13: 14, 14: 13, 15: 16, 16: 15, 17: 18, 18: 17,
               10: 9, 9: 10}


def _p_community_id(cfg: dict) -> Callable[[dict], None]:
    seed = int(cfg.get("seed", 0))
    if not 0 <= seed <= 0xFFFF:
        raise IngestProcessorException(
            f"community_id seed [{seed}] must be in [0, 65535]")
    target = cfg.get("target_field", "network.community_id")

    def p(doc):
        sip = _get_path(doc, cfg.get("source_ip", "source.ip"))
        dip = _get_path(doc, cfg.get("destination_ip", "destination.ip"))
        proto = _get_path(doc, cfg.get("transport", "network.transport"))
        sport = _get_path(doc, cfg.get("source_port", "source.port"))
        dport = _get_path(doc, cfg.get("destination_port",
                                       "destination.port"))
        if sip is None or dip is None or proto is None:
            if cfg.get("ignore_missing", True):
                return
            raise IngestProcessorException("community_id fields missing")
        pnum = (_PROTO_NUM.get(str(proto).lower())
                if not str(proto).isdigit() else int(proto))
        if pnum is None:
            raise IngestProcessorException(
                f"unsupported transport [{proto}]")
        try:
            a = ipaddress.ip_address(str(sip))
            b = ipaddress.ip_address(str(dip))
            if pnum in (1, 58):
                # ICMP flows use (type, code-equivalent) as the port pair
                # (Community ID spec; the reference reads icmp.type/code)
                itype = _get_path(doc, cfg.get("icmp_type", "icmp.type"))
                icode = _get_path(doc, cfg.get("icmp_code", "icmp.code"))
                sp = int(itype) & 0xFFFF if itype is not None else 0
                if sp in _ICMP_EQUIV:
                    dp = _ICMP_EQUIV[sp]
                else:
                    dp = int(icode) & 0xFFFF if icode is not None else 0
            else:
                sp = int(sport or 0) & 0xFFFF
                dp = int(dport or 0) & 0xFFFF
        except (ValueError, TypeError) as e:
            raise IngestProcessorException(str(e))
        # one-way ICMP types (no equivalent) are NOT endpoint-swapped; all
        # other flows canonicalize smaller (ip, port) endpoint first
        oneway = pnum in (1, 58) and sp not in _ICMP_EQUIV
        if not oneway and (b.packed, dp) < (a.packed, sp):
            a, b, sp, dp = b, a, dp, sp
        data = (struct.pack("!H", seed) + a.packed + b.packed
                + struct.pack("!BBHH", pnum, 0, sp, dp))
        digest = base64.b64encode(hashlib.sha1(data).digest()).decode()
        _set_path(doc, target, f"1:{digest}")
    return p


# --------------------------------------------------------------- user_agent

# Compact rule table standing in for the uap-core corpus the reference
# plugin bundles (modules/ingest-user-agent/.../IngestUserAgentModulePlugin
# loads regexes.yml). Order matters: first match wins.
_UA_BOTS = re.compile(
    r"(Googlebot|Bingbot|bingbot|Slurp|DuckDuckBot|Baiduspider|YandexBot|"
    r"facebookexternalhit|Twitterbot|Applebot|AhrefsBot|SemrushBot|"
    r"crawler|spider|bot)", re.I)
_UA_BROWSERS = [
    ("Edge", re.compile(r"Edge?/(\d+)(?:\.(\d+))?(?:\.(\d+))?")),
    ("Opera", re.compile(r"OPR/(\d+)(?:\.(\d+))?(?:\.(\d+))?")),
    ("Samsung Internet",
     re.compile(r"SamsungBrowser/(\d+)(?:\.(\d+))?")),
    ("Chrome Mobile",
     re.compile(r"Chrome/(\d+)(?:\.(\d+))?(?:\.(\d+))?[\d.]* Mobile")),
    ("Chrome", re.compile(r"Chrome/(\d+)(?:\.(\d+))?(?:\.(\d+))?")),
    ("Firefox Mobile",
     re.compile(r"Firefox/(\d+)(?:\.(\d+))?.*Mobile|Mobile.*Firefox/(\d+)")),
    ("Firefox", re.compile(r"Firefox/(\d+)(?:\.(\d+))?(?:\.(\d+))?")),
    ("Mobile Safari",
     re.compile(r"Version/(\d+)(?:\.(\d+))?(?:\.(\d+))?.*Mobile.*Safari")),
    ("Safari", re.compile(r"Version/(\d+)(?:\.(\d+))?(?:\.(\d+))?.*Safari")),
    ("IE", re.compile(r"MSIE (\d+)(?:\.(\d+))?|Trident/.*rv:(\d+)")),
]
_UA_OS = [
    ("Windows", re.compile(r"Windows NT (\d+)\.(\d+)"),
     {"10.0": "10", "6.3": "8.1", "6.2": "8", "6.1": "7", "6.0": "Vista",
      "5.1": "XP"}),
    ("iOS", re.compile(r"(?:iPhone|CPU) OS (\d+)_(\d+)(?:_(\d+))?"), None),
    ("Mac OS X", re.compile(r"Mac OS X (\d+)[._](\d+)(?:[._](\d+))?"), None),
    ("Android", re.compile(r"Android (\d+)(?:\.(\d+))?(?:\.(\d+))?"), None),
    ("Chrome OS", re.compile(r"CrOS \S+ (\d+)\.(\d+)"), None),
    ("Ubuntu", re.compile(r"Ubuntu"), None),
    ("Linux", re.compile(r"Linux"), None),
]


def parse_user_agent(ua: str) -> dict:
    """ECS-shaped parse: {name, version, os{name,version,full}, device{name}}."""
    out: dict = {"name": "Other", "device": {"name": "Other"}}
    if _UA_BOTS.search(ua):
        m = _UA_BOTS.search(ua)
        out["name"] = m.group(1)
        out["device"]["name"] = "Spider"
        return out
    for name, rx in _UA_BROWSERS:
        m = rx.search(ua)
        if m:
            out["name"] = name
            ver = [g for g in m.groups() if g is not None]
            if ver:
                out["version"] = ".".join(ver)
            break
    for name, rx, vmap in _UA_OS:
        m = rx.search(ua)
        if m:
            os_d: dict = {"name": name}
            groups = [g for g in m.groups() if g is not None]
            if groups:
                ver = ".".join(groups)
                if vmap:
                    ver = vmap.get(ver, ver)
                os_d["version"] = ver
                os_d["full"] = f"{name} {ver}"
            out["os"] = os_d
            break
    if "iPad" in ua:
        out["device"]["name"] = "iPad"
    elif "iPhone" in ua:
        out["device"]["name"] = "iPhone"
    elif "Android" in ua:
        out["device"]["name"] = ("Generic Smartphone" if "Mobile" in ua
                                 else "Generic Tablet")
    elif "Macintosh" in ua:
        out["device"]["name"] = "Mac"
    return out


def _p_user_agent(cfg: dict) -> Callable[[dict], None]:
    field = cfg.get("field", "user_agent")
    target = cfg.get("target_field", "user_agent")
    props = set(cfg.get("properties", []) or [])

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(
                f"field [{field}] is null, cannot parse user-agent.")
        parsed = parse_user_agent(str(v))
        parsed["original"] = str(v)
        if props:
            parsed = {k: x for k, x in parsed.items() if k in props}
        _set_path(doc, target, parsed)
    return p


# -------------------------------------------------------------------- geoip

# Built-in resolver table: well-known public ranges only, enough to make the
# processor's field contract and range semantics real. Operators load real
# data via database_file (JSON: {"cidr": {country_iso_code: ..., ...}}).
_GEO_BUILTIN = {
    "8.8.8.0/24": {"country_iso_code": "US", "country_name": "United States",
                   "continent_name": "North America",
                   "location": {"lat": 37.751, "lon": -97.822},
                   "timezone": "America/Chicago"},
    "8.8.4.0/24": {"country_iso_code": "US", "country_name": "United States",
                   "continent_name": "North America",
                   "location": {"lat": 37.751, "lon": -97.822}},
    "1.1.1.0/24": {"country_iso_code": "AU", "country_name": "Australia",
                   "continent_name": "Oceania",
                   "location": {"lat": -33.494, "lon": 143.2104}},
    "9.9.9.0/24": {"country_iso_code": "US", "country_name": "United States",
                   "continent_name": "North America"},
    "208.67.222.0/24": {"country_iso_code": "US",
                        "country_name": "United States",
                        "continent_name": "North America",
                        "city_name": "San Francisco",
                        "region_name": "California",
                        "region_iso_code": "US-CA",
                        "location": {"lat": 37.7749, "lon": -122.4194}},
    # RFC 5737 documentation ranges, mapped for tests/examples
    "192.0.2.0/24": {"country_iso_code": "US",
                     "country_name": "United States",
                     "continent_name": "North America",
                     "city_name": "Example City",
                     "location": {"lat": 40.0, "lon": -100.0}},
    "198.51.100.0/24": {"country_iso_code": "DE", "country_name": "Germany",
                        "continent_name": "Europe",
                        "city_name": "Berlin",
                        "location": {"lat": 52.52, "lon": 13.405}},
    "203.0.113.0/24": {"country_iso_code": "JP", "country_name": "Japan",
                       "continent_name": "Asia", "city_name": "Tokyo",
                       "location": {"lat": 35.6762, "lon": 139.6503}},
}
_GEO_DEFAULT_PROPS = ("continent_name", "country_name", "country_iso_code",
                      "region_iso_code", "region_name", "city_name",
                      "location")


class GeoDatabase:
    def __init__(self, table: dict):
        self.nets = sorted(
            ((ipaddress.ip_network(c), dict(v)) for c, v in table.items()),
            key=lambda nv: -nv[0].prefixlen)

    def lookup(self, ip: str) -> Optional[dict]:
        addr = ipaddress.ip_address(ip)
        for net, v in self.nets:
            if addr in net:
                return v
        return None


_BUILTIN_DB = GeoDatabase(_GEO_BUILTIN)


def _p_geoip(cfg: dict) -> Callable[[dict], None]:
    field = cfg["field"]
    target = cfg.get("target_field", "geoip")
    props = set(cfg.get("properties", _GEO_DEFAULT_PROPS))
    db = _BUILTIN_DB
    if cfg.get("database_file"):
        try:
            with open(cfg["database_file"]) as f:
                db = GeoDatabase(_json.load(f))
        except (OSError, ValueError) as e:
            raise IngestProcessorException(
                f"cannot load geoip database [{cfg['database_file']}]: {e}")

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(
                f"field [{field}] is null, cannot extract geoip information.")
        try:
            ipaddress.ip_address(str(v))
        except ValueError:
            raise IngestProcessorException(f"[{v}] is not an IP address")
        # database hit wins; private/reserved/unknown addresses resolve to
        # nothing, silently (the reference's behavior for addresses absent
        # from the database)
        geo = db.lookup(str(v))
        if geo is None:
            return
        _set_path(doc, target, {k: x for k, x in geo.items() if k in props})
    return p


# --------------------------------------------------------------- attachment

def _p_attachment(cfg: dict) -> Callable[[dict], None]:
    from .attachment import extract
    field = cfg["field"]
    target = cfg.get("target_field", "attachment")
    props = set(cfg.get("properties", []) or [])
    limit = int(cfg.get("indexed_chars", 100_000))
    limit_field = cfg.get("indexed_chars_field")

    def p(doc):
        v = _get_path(doc, field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(
                f"field [{field}] is null, cannot parse.")
        try:
            raw = base64.b64decode(v, validate=False) \
                if isinstance(v, str) else bytes(v)
        except (binascii.Error, ValueError) as e:
            raise IngestProcessorException(
                f"field [{field}] is not valid base64: {e}")
        lim = limit
        if limit_field:
            lf = _get_path(doc, limit_field)
            if lf is not None:
                try:
                    lim = int(lf)
                except (TypeError, ValueError):
                    raise IngestProcessorException(
                        f"field [{limit_field}] is not an integer")
        try:
            parsed = extract(raw, indexed_chars=lim)
        except Exception as e:
            raise IngestProcessorException(
                f"Error parsing document in field [{field}]: {e}")
        if props:
            parsed = {k: x for k, x in parsed.items() if k in props}
        _set_path(doc, target, parsed)
        if cfg.get("remove_binary", False):
            _del_path(doc, field)
    return p


EXTRA_PROCESSORS = {
    "json": _p_json,
    "kv": _p_kv,
    "dissect": _p_dissect,
    "csv": _p_csv,
    "bytes": _p_bytes,
    "urldecode": _p_urldecode,
    "uri_parts": _p_uri_parts,
    "html_strip": _p_html_strip,
    "fingerprint": _p_fingerprint,
    "sort": _p_sort,
    "dot_expander": _p_dot_expander,
    "remove_by_pattern": _p_remove_by_pattern,
    "date_index_name": _p_date_index_name,
    "community_id": _p_community_id,
    "user_agent": _p_user_agent,
    "geoip": _p_geoip,
    "attachment": _p_attachment,
}

# factories that also need the IngestService (nested processor compilation)
EXTRA_PROCESSORS_WITH_SERVICE = {
    "foreach": _p_foreach,
}
