"""Ingest pipelines. Analog of reference `ingest/IngestService.java` +
`modules/ingest-common` processors. Pipelines run on the host before a doc
reaches the engine (exactly like the reference runs them on the ingest node
before the shard bulk)."""

from __future__ import annotations

import copy
import datetime as _dt
import re
import threading as _threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from ..obs import ingest_obs as _iobs


class IngestProcessorException(Exception):
    pass


def _get_path(doc: dict, path: str, default=None):
    node: Any = doc
    for p in path.split("."):
        if not isinstance(node, dict) or p not in node:
            return default
        node = node[p]
    return node


def _set_path(doc: dict, path: str, value) -> None:
    node = doc
    parts = path.split(".")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _del_path(doc: dict, path: str) -> None:
    node = doc
    parts = path.split(".")
    for p in parts[:-1]:
        if not isinstance(node, dict) or p not in node:
            return
        node = node[p]
    if isinstance(node, dict):
        node.pop(parts[-1], None)


class DropDocument(Exception):
    """Raised by the drop processor: the doc is silently discarded."""


# per-thread set of pipeline names currently executing (cycle guard for
# the nested `pipeline` processor)
_ACTIVE_PIPELINES = _threading.local()


def _render(template: str, doc: dict) -> str:
    """Tiny mustache: {{field}} substitution (reference lang-mustache)."""
    return re.sub(r"\{\{\s*([\w.]+)\s*\}\}",
                  lambda m: str(_get_path(doc, m.group(1), "")), template)


def build_processor(kind: str, cfg: dict,
                    service=None) -> Callable[[dict], None]:  # noqa: C901
    if kind == "set":
        field, value = cfg["field"], cfg.get("value")
        override = cfg.get("override", True)

        def p_set(doc):
            if override or _get_path(doc, field) is None:
                v = _render(value, doc) if isinstance(value, str) else value
                _set_path(doc, field, v)
        return p_set

    if kind == "remove":
        fields = cfg["field"] if isinstance(cfg["field"], list) else [cfg["field"]]
        return lambda doc: [_del_path(doc, f) for f in fields] and None

    if kind == "rename":
        src, dst = cfg["field"], cfg["target_field"]

        def p_rename(doc):
            v = _get_path(doc, src)
            if v is None:
                if not cfg.get("ignore_missing", False):
                    raise IngestProcessorException(f"field [{src}] not present")
                return
            _set_path(doc, dst, v)
            _del_path(doc, src)
        return p_rename

    if kind == "convert":
        field = cfg["field"]
        target = cfg.get("target_field", field)
        typ = cfg["type"]

        def p_convert(doc):
            v = _get_path(doc, field)
            if v is None:
                if not cfg.get("ignore_missing", False):
                    raise IngestProcessorException(f"field [{field}] not present")
                return
            try:
                if typ == "integer" or typ == "long":
                    out: Any = int(v)
                elif typ == "float" or typ == "double":
                    out = float(v)
                elif typ == "boolean":
                    out = str(v).lower() in ("true", "1", "yes")
                elif typ == "string":
                    out = str(v)
                elif typ == "auto":
                    try:
                        out = int(v)
                    except (TypeError, ValueError):
                        try:
                            out = float(v)
                        except (TypeError, ValueError):
                            out = v
                else:
                    raise IngestProcessorException(f"unknown convert type [{typ}]")
            except (TypeError, ValueError) as e:
                raise IngestProcessorException(str(e))
            _set_path(doc, target, out)
        return p_convert

    if kind in ("lowercase", "uppercase", "trim"):
        field = cfg["field"]
        fn = {"lowercase": str.lower, "uppercase": str.upper, "trim": str.strip}[kind]

        def p_str(doc):
            v = _get_path(doc, field)
            if isinstance(v, str):
                _set_path(doc, field, fn(v))
            elif isinstance(v, list):
                _set_path(doc, field, [fn(x) if isinstance(x, str) else x for x in v])
        return p_str

    if kind == "split":
        field, sep = cfg["field"], cfg["separator"]
        return lambda doc: _set_path(doc, cfg.get("target_field", field),
                                     re.split(sep, _get_path(doc, field, "")))

    if kind == "join":
        field, sep = cfg["field"], cfg["separator"]
        return lambda doc: _set_path(doc, cfg.get("target_field", field),
                                     sep.join(str(x) for x in _get_path(doc, field, [])))

    if kind == "gsub":
        field = cfg["field"]
        pat = re.compile(cfg["pattern"])
        rep = cfg["replacement"]
        return lambda doc: _set_path(doc, cfg.get("target_field", field),
                                     pat.sub(rep, str(_get_path(doc, field, ""))))

    if kind == "append":
        field, value = cfg["field"], cfg["value"]

        def p_append(doc):
            cur = _get_path(doc, field)
            vals = value if isinstance(value, list) else [value]
            if cur is None:
                _set_path(doc, field, list(vals))
            elif isinstance(cur, list):
                cur.extend(vals)
            else:
                _set_path(doc, field, [cur] + list(vals))
        return p_append

    if kind == "date":
        field = cfg["field"]
        target = cfg.get("target_field", "@timestamp")
        formats = cfg.get("formats", ["ISO8601"])

        def p_date(doc):
            v = _get_path(doc, field)
            for fmt in formats:
                try:
                    if fmt in ("ISO8601", "strict_date_optional_time"):
                        d = _dt.datetime.fromisoformat(str(v).replace("Z", "+00:00"))
                    elif fmt == "UNIX":
                        d = _dt.datetime.fromtimestamp(float(v), _dt.timezone.utc)
                    elif fmt == "UNIX_MS":
                        d = _dt.datetime.fromtimestamp(float(v) / 1000, _dt.timezone.utc)
                    else:
                        d = _dt.datetime.strptime(str(v), fmt)
                    if d.tzinfo is None:
                        d = d.replace(tzinfo=_dt.timezone.utc)
                    _set_path(doc, target, d.isoformat().replace("+00:00", "Z"))
                    return
                except (ValueError, TypeError):
                    continue
            raise IngestProcessorException(f"unable to parse date [{v}]")
        return p_date

    if kind == "grok":
        field = cfg["field"]
        patterns = cfg["patterns"]
        compiled = [_grok_compile(p) for p in patterns]

        def p_grok(doc):
            v = str(_get_path(doc, field, ""))
            for rx in compiled:
                m = rx.match(v)
                if m:
                    for k, val in m.groupdict().items():
                        if val is not None:
                            _set_path(doc, k, val)
                    return
            if not cfg.get("ignore_missing", False):
                raise IngestProcessorException("grok patterns do not match")
        return p_grok

    if kind == "drop":
        def p_drop(doc):
            raise DropDocument()
        return p_drop

    if kind == "fail":
        msg = cfg.get("message", "fail processor triggered")

        def p_fail(doc):
            raise IngestProcessorException(_render(msg, doc))
        return p_fail

    if kind == "script":
        from ..script import ScriptError, run_ingest_script
        from ..script.painless_lite import parse as parse_script
        src = cfg.get("source", cfg.get("inline", ""))
        if not src:
            raise IngestProcessorException("script processor requires [source]")
        try:
            parse_script(src)  # reject bad scripts at pipeline PUT, not per-doc
        except ScriptError as e:
            raise IngestProcessorException(f"script compile error: {e}")
        params = cfg.get("params") or {}

        def p_script(doc):
            try:
                run_ingest_script(src, params, doc)
            except ScriptError as e:
                raise IngestProcessorException(f"script processor failed: {e}")
        return p_script

    if kind == "pipeline":
        if service is None:
            raise IngestProcessorException(
                "nested pipeline processor requires service context")
        name = cfg["name"]

        def p_pipeline(doc):
            inner = service.get_pipeline(name)
            if inner is None:
                raise IngestProcessorException(
                    f"non-existent pipeline [{name}]")
            # cycle guard (reference: "Cycle detected for pipeline: ...")
            active = _ACTIVE_PIPELINES.__dict__.setdefault("names", set())
            if name in active:
                raise IngestProcessorException(
                    f"Cycle detected for pipeline: {name}")
            active.add(name)
            try:
                if inner.run(doc) is None:
                    raise DropDocument()
            finally:
                active.discard(name)
        return p_pipeline

    from .ext import EXTRA_PROCESSORS, EXTRA_PROCESSORS_WITH_SERVICE
    factory = EXTRA_PROCESSORS_WITH_SERVICE.get(kind)
    if factory is not None:
        return factory(cfg, service)
    factory = EXTRA_PROCESSORS.get(kind)
    if factory is not None:
        return factory(cfg)

    raise IngestProcessorException(f"unknown processor type [{kind}]")


_GROK_BASE = {
    "WORD": r"\w+", "NUMBER": r"[-+]?\d+(?:\.\d+)?", "INT": r"[-+]?\d+",
    "IP": r"\d{1,3}(?:\.\d{1,3}){3}", "LOGLEVEL": r"[A-Za-z]+",
    "TIMESTAMP_ISO8601": r"\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}:\d{2}(?:[.,]\d+)?(?:Z|[+-]\d{2}:?\d{2})?",
    "GREEDYDATA": r".*", "DATA": r".*?", "NOTSPACE": r"\S+", "SPACE": r"\s*",
    "USERNAME": r"[a-zA-Z0-9._-]+", "UUID": r"[0-9a-fA-F-]{36}",
}


def _grok_compile(pattern: str) -> re.Pattern:
    def repl(m):
        name, alias = m.group(1), m.group(2)
        base = _GROK_BASE.get(name, r".*?")
        if alias:
            safe = alias.replace(".", "_DOT_")
            return f"(?P<{safe}>{base})"
        return f"(?:{base})"

    rx = re.sub(r"%\{(\w+)(?::([\w.]+))?\}", repl, pattern)
    compiled = re.compile(rx)
    return compiled


class Pipeline:
    def __init__(self, pid: str, config: dict, service=None):
        self.id = pid
        # deep-copy: callers keep (and may mutate) their dict; GET /
        # _simulate must reflect only what was actually PUT
        self.config = copy.deepcopy(config)
        self.description = config.get("description", "")
        self.processors: List[tuple] = []
        for pspec in config.get("processors", []):
            ((kind, cfg),) = pspec.items()
            self.processors.append(
                (kind, cfg, build_processor(kind, cfg, service),
                 cfg.get("ignore_failure", False),
                 [build_processor(*next(iter(f.items())), service)
                  for f in cfg.get("on_failure", [])]))

    def run(self, doc: dict) -> Optional[dict]:
        """Returns the transformed doc, or None when dropped."""
        for kind, cfg, proc, ignore_failure, on_failure in self.processors:
            try:
                proc(doc)
            except DropDocument:
                return None
            except IngestProcessorException:
                if on_failure:
                    # the on_failure chain replaces (and swallows) the
                    # original error — count it or it vanishes without
                    # a trace (write-path swallowed-exception audit)
                    _iobs.count("indexing.pipeline.failed")
                    for fp in on_failure:
                        fp(doc)
                elif ignore_failure:
                    # swallowed silently by config — still counted
                    _iobs.count("indexing.pipeline.failed")
                else:
                    raise
        return doc


class IngestService:
    def __init__(self):
        self.pipelines: Dict[str, Pipeline] = {}

    def put_pipeline(self, pid: str, config: dict) -> None:
        self.pipelines[pid] = Pipeline(pid, config, service=self)

    def delete_pipeline(self, pid: str) -> None:
        self.pipelines.pop(pid, None)

    def get_pipeline(self, pid: str) -> Optional[Pipeline]:
        return self.pipelines.get(pid)

    def run(self, pid: str, doc: dict) -> Optional[dict]:
        p = self.pipelines.get(pid)
        if p is None:
            raise IngestProcessorException(f"pipeline [{pid}] does not exist")
        if not _iobs.enabled():
            return p.run(doc)
        t0 = _time.perf_counter()
        out = p.run(doc)
        _iobs.record_pipeline((_time.perf_counter() - t0) * 1000.0,
                              out is None)
        return out

    def simulate(self, config: dict, docs: List[dict]) -> List[dict]:
        p = Pipeline("_simulate", config, service=self)
        out = []
        for d in docs:
            src = dict(d.get("_source", d))
            try:
                res = p.run(src)
                out.append({"doc": {"_source": res}} if res is not None
                           else {"doc": None, "dropped": True})
            except IngestProcessorException as e:
                out.append({"error": {"type": "ingest_processor_exception",
                                      "reason": str(e)}})
        return out
