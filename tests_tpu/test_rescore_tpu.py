"""TPU-hardware parity for the device-batched phase-2 rescore
(ops/rescore.py): on a real chip the batched kernel must reproduce the host
numpy oracle BIT-FOR-BIT — exact f32 scores, match counts, and the
serve/escalate decisions the escalation ladder makes on them. Run on a real
chip: `python -m pytest tests_tpu/test_rescore_tpu.py -q`."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opensearch_tpu.ops.pallas_bm25 import (DL_BITS, INT_SENTINEL, LANES,
                                            align_csr_rows)
from opensearch_tpu.ops.rescore import (exact_rescore_batch,
                                        host_exact_rescore_batch)
from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.search import fastpath

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


@pytest.mark.parametrize("seed", [5, 23])
def test_kernel_bitwise_parity_on_silicon(seed):
    """Raw kernel vs numpy mirror over the same padded operands — exact
    f32 byte equality (the _tie_serves/theta32 contract), not allclose."""
    rng = np.random.default_rng(seed)
    nterms, ndocs = 6, 50_000
    starts_l = [0]
    docs, tfdl = [], []
    for _ in range(nterms):
        df = int(rng.integers(10, 8000))
        ids = np.sort(rng.choice(ndocs, size=df, replace=False))
        tf = rng.integers(1, 30, df)
        dl = rng.integers(1, 500, df)
        docs.append(ids.astype(np.int32))
        tfdl.append(((tf.astype(np.int64) << DL_BITS) | dl).astype(np.int32))
        starts_l.append(starts_l[-1] + df)
    a_starts, a_docs, a_tfdl = align_csr_rows(
        np.asarray(starts_l, np.int64), np.concatenate(docs),
        np.concatenate(tfdl), margin=1024, alignment=LANES)
    T, C, QB = 4, 1024, 8
    starts = np.zeros((QB, T), np.int32)
    lens = np.zeros((QB, T), np.int32)
    weights = np.zeros((QB, T), np.float32)
    avgdl = np.zeros((QB, 1), np.float32)
    cand = np.full((QB, C), INT_SENTINEL, np.int32)
    for q in range(QB):
        for t in range(T):
            if rng.random() < 0.2:
                continue
            r = int(rng.integers(0, nterms))
            a, b = int(a_starts[r]), int(a_starts[r + 1])
            starts[q, t] = a
            lens[q, t] = int(np.sum(a_docs[a:b] != INT_SENTINEL))
            weights[q, t] = np.float32(rng.uniform(0.1, 4.0))
        avgdl[q, 0] = np.float32(rng.uniform(1.0, 300.0))
        n = int(rng.integers(1, C))
        cand[q, :n] = np.sort(rng.choice(ndocs, size=n, replace=False))
    for k1, b in ((1.2, 0.75), (0.9, 0.0)):
        dx, dc = exact_rescore_batch(
            jnp.asarray(a_docs), jnp.asarray(a_tfdl), starts, lens,
            weights, avgdl, cand, T=T, C=C, k1=k1, b=b)
        hx, hc = host_exact_rescore_batch(
            a_docs, a_tfdl, starts, lens, weights, avgdl, cand, k1=k1, b=b)
        assert np.asarray(dx).tobytes() == hx.tobytes()
        assert (np.asarray(dc) == hc).all()


@pytest.fixture(scope="module")
def client(request):
    # shrink L_HEAD so a 20k-doc corpus genuinely clamps and the verify
    # rung actually escalates into the phase-2 rescore
    orig = fastpath.L_HEAD
    fastpath.L_HEAD = 256
    request.addfinalizer(lambda: setattr(fastpath, "L_HEAD", orig))
    rng = np.random.default_rng(7)
    words = [f"w{i}" for i in range(400)]
    c = RestClient()
    c.indices.create("ridx")
    bulk = []
    for i in range(20_000):
        parts = list(rng.choice(words, size=10))
        if rng.random() < 0.6:
            parts.extend(["common"] * int(rng.integers(1, 4)))
        if rng.random() < 0.4:
            parts.append("semi")
        bulk.append({"index": {"_index": "ridx", "_id": str(i)}})
        bulk.append({"body": " ".join(parts)})
    c.bulk(bulk)
    c.indices.refresh("ridx")
    c.indices.forcemerge("ridx")
    return c


@pytest.mark.parametrize("body", [
    {"query": {"match": {"body": "common semi"}}, "size": 10},
    {"query": {"match": {"body": "common w3 semi"}}, "size": 10},
    {"query": {"match": {"body": {"query": "common semi",
                                  "operator": "and"}}}, "size": 10},
])
def test_serve_decisions_host_vs_device(client, body):
    """End-to-end on silicon: same served pages, bit-identical scores, and
    the same serve/dense split whichever side runs the middle rung."""
    c = client
    outs, splits = {}, {}
    keys = ("pruned_served", "pruned_rescued", "pruned_rescued2",
            "pruned_escalated")
    for i, mode in enumerate(("host", "device")):
        fastpath.set_rescore_mode(mode)
        before = dict(fastpath.STATS)
        try:
            # _ref busts the request cache between the two runs
            outs[mode] = c.search(index="ridx", body=dict(body, _ref=i))
        finally:
            fastpath.set_rescore_mode(None)
        splits[mode] = {k: fastpath.STATS[k] - before[k] for k in keys}
    assert splits["host"] == splits["device"], body
    h, d = outs["host"], outs["device"]
    assert [(x["_id"], x["_score"]) for x in h["hits"]["hits"]] == \
        [(x["_id"], x["_score"]) for x in d["hits"]["hits"]], body
    assert h["hits"]["total"] == d["hits"]["total"]


def test_device_rescore_engaged(client):
    """The device path actually launched (RESCORE_STATS moved) for an
    escalating msearch batch, grouped into few launches."""
    c = client
    before = dict(fastpath.RESCORE_STATS)
    fastpath.set_rescore_mode("device")
    try:
        lines = []
        for i in range(8):
            lines.append({"index": "ridx"})
            lines.append({"query": {"match": {"body": "common semi"}},
                          "size": 10, "_ref": 100 + i})
        c.msearch(lines)
    finally:
        fastpath.set_rescore_mode(None)
    dq = fastpath.RESCORE_STATS["device_queries"] - before["device_queries"]
    dl = fastpath.RESCORE_STATS["device_launches"] \
        - before["device_launches"]
    if dq == 0:
        pytest.skip("no query escalated into the phase-2 rung")
    assert dl <= dq
