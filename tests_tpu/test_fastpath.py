"""TPU-hardware parity tests for the Pallas production fast path.

Run on a machine with a real TPU chip (NOT under tests/conftest.py, which
pins the CPU backend): `python -m pytest tests_tpu/ -q`.

Asserts the fused kernel path returns bit-identical hits/totals to the XLA
gather→scatter path through the REST client, including the doc-range chunked
decomposition for huge posting rows and the batched msearch path.
"""

import numpy as np
import pytest

import jax

from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.search import fastpath

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


@pytest.fixture(scope="module")
def client():
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(300)]
    c = RestClient()
    c.indices.create("idx")
    bulk = []
    for i in range(4000):
        parts = list(rng.choice(words, size=12))
        if rng.random() < 0.6:
            parts.append("common")
        bulk.append({"index": {"_index": "idx", "_id": str(i)}})
        bulk.append({"body": " ".join(parts)})
    c.bulk(bulk)
    c.indices.refresh("idx")
    return c


def _both(c, body):
    fastpath.set_enabled(True)
    fast = c.search(index="idx", body=body)
    fastpath.set_enabled(False)
    slow = c.search(index="idx", body=body)
    fastpath.set_enabled(True)
    return fast, slow


def _hits(resp):
    return [(h["_id"], round(h["_score"], 6)) for h in resp["hits"]["hits"]]


QUERIES = [
    {"query": {"match": {"body": "w1 w2"}}, "size": 10},
    {"query": {"term": {"body": "w5"}}, "size": 5},
    {"query": {"match": {"body": {"query": "w3 w7 w11",
                                  "minimum_should_match": 2}}}, "size": 7},
    {"query": {"match": {"body": {"query": "w0 w250",
                                  "operator": "and"}}}, "size": 10},
    {"query": {"terms": {"body": ["w8", "w9", "w10"]}}, "size": 10},
    {"query": {"match": {"body": "common w4"}}, "size": 10},
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_parity_vs_xla(client, qi):
    body = QUERIES[qi]
    # unique marker defeats the request cache
    body = dict(body, _probe=qi)
    fast, slow = _both(client, body)
    assert fast["hits"]["total"] == slow["hits"]["total"]
    assert _hits(fast) == _hits(slow)


def test_fastpath_engaged(client):
    client.search(index="idx", body={"query": {"match": {"body": "w1"}}})
    eng = client.node.indices["idx"].shards[0]
    seg = eng.segments[0]
    al = getattr(seg, "_fastpath_aligned", None)
    assert al and al.get("body") is not None


def test_chunked_oversized_rows(client):
    old_l, old_tl = fastpath.MAX_L, fastpath.MAX_TL
    fastpath.MAX_L, fastpath.MAX_TL = 1 << 11, 1 << 12
    try:
        # prove the decomposition actually engages at these caps
        from opensearch_tpu.search import compiler as C
        from opensearch_tpu.search import query_dsl as dsl
        from opensearch_tpu.search.executor import ShardSearcher
        eng = client.node.indices["idx"].shards[0]
        s = ShardSearcher(eng)
        ctx = s.context()
        lt = C.rewrite(dsl.parse_query({"match": {"body": "common w17"}}),
                       ctx, scoring=True)
        vls = fastpath._prepare_vqueries(eng.segments[0], ctx, [lt], {})
        assert vls[0] is not None and len(vls[0]) >= 2
        body = {"query": {"match": {"body": "common w17"}}, "size": 10,
                "_probe": "chunk"}
        fast, slow = _both(client, body)
        assert fast["hits"]["total"] == slow["hits"]["total"]
        assert _hits(fast) == _hits(slow)
    finally:
        fastpath.MAX_L, fastpath.MAX_TL = old_l, old_tl


def test_high_tf_packing(client):
    """tf in [1024, 2047] sets the i32 sign bit in the packed tf·dl word;
    the kernel must mask after its arithmetic shift (regression)."""
    c = RestClient()
    c.indices.create("hightf")
    c.index("hightf", {"body": "word " * 1500 + "other"}, id="big")
    c.index("hightf", {"body": "word other things"}, id="small")
    c.indices.refresh("hightf")
    for qi, q in enumerate(("word other", "word")):
        body = {"query": {"match": {"body": q}}, "size": 5, "_p": qi}
        fastpath.set_enabled(True)
        fast = c.search(index="hightf", body=body)
        fastpath.set_enabled(False)
        slow = c.search(index="hightf", body=body)
        fastpath.set_enabled(True)
        assert _hits(fast) == _hits(slow)
    # single-term: tf saturation beats length norm -> 1500x doc wins; a
    # sign-extended tf would send its score negative instead
    assert fast["hits"]["hits"][0]["_id"] == "big"


def test_msearch_batched_parity(client):
    msb = []
    for q in ("w1 w2", "w5", "w3 w7 w11", "common w250"):
        msb += [{"index": "idx"}, {"query": {"match": {"body": q}},
                                   "size": 5}]
    fastpath.set_enabled(True)
    fast = client.msearch(msb)
    fastpath.set_enabled(False)
    slow = client.msearch(msb)
    fastpath.set_enabled(True)
    for a, b in zip(fast["responses"], slow["responses"]):
        assert a["hits"]["total"] == b["hits"]["total"]
        assert _hits(a) == _hits(b)
