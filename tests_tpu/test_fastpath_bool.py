"""TPU-hardware parity tests for the bool/filtered Pallas fast path
(`ops/pallas_bm25.fused_bm25_bool_topk` via `search/fastpath._run_bool`).

Asserts the weighted-threshold kernel returns the same hits/totals/scores
(6dp) as the XLA plan path through the REST client for the Lucene
BooleanQuery shapes real workloads run: filtered match, must/should with
minimum_should_match, must_not, constant_score, filter-only — including the
doc-range chunked decomposition with a filter slot.

Run on a machine with a real TPU chip: `python -m pytest tests_tpu/ -q`.
"""

import numpy as np
import pytest

import jax

from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.search import fastpath

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


@pytest.fixture(scope="module")
def client():
    rng = np.random.default_rng(42)
    words = [f"w{i}" for i in range(300)]
    statuses = ["published", "draft", "archived"]
    c = RestClient()
    c.indices.create("bidx", body={"mappings": {"properties": {
        "body": {"type": "text"},
        "status": {"type": "keyword"},
        "price": {"type": "integer"},
    }}})
    bulk = []
    for i in range(5000):
        parts = list(rng.choice(words, size=10))
        if rng.random() < 0.5:
            parts.append("common")
        bulk.append({"index": {"_index": "bidx", "_id": str(i)}})
        bulk.append({"body": " ".join(parts),
                     "status": statuses[int(rng.integers(0, 3))],
                     "price": int(rng.integers(0, 1000))})
    c.bulk(bulk)
    c.indices.refresh("bidx")
    return c


def _both(c, body):
    fastpath.set_enabled(True)
    before = dict(fastpath.STATS)
    fast = c.search(index="bidx", body=body)
    engaged = fastpath.STATS["bool_served"] > before["bool_served"]
    fastpath.set_enabled(False)
    slow = c.search(index="bidx", body=body)
    fastpath.set_enabled(True)
    return fast, slow, engaged


def _hits(resp):
    return [(h["_id"], round(h["_score"], 6)) for h in resp["hits"]["hits"]]


FILTER_PUB = {"term": {"status": "published"}}
FILTER_PRICE = {"range": {"price": {"gte": 200, "lt": 700}}}

QUERIES = [
    # filtered match — the canonical production shape
    {"query": {"bool": {"must": [{"match": {"body": "w1 w2"}}],
                        "filter": [FILTER_PUB]}}, "size": 10},
    # filter range + term must
    {"query": {"bool": {"must": [{"term": {"body": "w5"}}],
                        "filter": [FILTER_PRICE]}}, "size": 10},
    # two filters + must_not
    {"query": {"bool": {"must": [{"match": {"body": "common w9"}}],
                        "filter": [FILTER_PUB, FILTER_PRICE],
                        "must_not": [{"term": {"body": "w17"}}]}},
     "size": 10},
    # shoulds with minimum_should_match under a filter
    {"query": {"bool": {"should": [{"term": {"body": "w3"}},
                                   {"term": {"body": "w7"}},
                                   {"term": {"body": "w11"}}],
                        "minimum_should_match": 2,
                        "filter": [FILTER_PUB]}}, "size": 10},
    # multiple single-term musts, no filter (unfiltered bool kernel)
    {"query": {"bool": {"must": [{"term": {"body": "w2"}},
                                 {"term": {"body": "common"}}]}},
     "size": 10},
    # must multi-term group (internal msm) + filter
    {"query": {"bool": {"must": [{"match": {
        "body": {"query": "w3 w7 w11", "minimum_should_match": 2}}}],
        "filter": [FILTER_PUB]}}, "size": 10},
    # AND-operator match as must (all terms required) + filter
    {"query": {"bool": {"must": [{"match": {
        "body": {"query": "w0 common", "operator": "and"}}}],
        "filter": [FILTER_PRICE]}}, "size": 10},
    # bonus shoulds (msm=0 with must present) — score-only clauses
    {"query": {"bool": {"must": [{"term": {"body": "common"}}],
                        "should": [{"term": {"body": "w4"}},
                                   {"term": {"body": "w8"}}]}}, "size": 10},
    # filter-only bool: hits score 0, doc order
    {"query": {"bool": {"filter": [FILTER_PUB, FILTER_PRICE]}}, "size": 10},
    # constant_score
    {"query": {"constant_score": {"filter": FILTER_PUB, "boost": 2.5}},
     "size": 10},
    # must_not only
    {"query": {"bool": {"must": [{"term": {"body": "common"}}],
                        "must_not": [FILTER_PUB]}}, "size": 10},
    # boosted bool
    {"query": {"bool": {"must": [{"match": {"body": "w1 w2"}}],
                        "filter": [FILTER_PUB], "boost": 3.0}}, "size": 10},
    # filter matching nothing (may short-circuit to match_none at rewrite,
    # so engagement is not asserted — parity still is)
    {"query": {"bool": {"must": [{"term": {"body": "common"}}],
                        "filter": [{"term": {"status": "missingno"}}]}},
     "size": 10, "_noengage": True},
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_bool_parity_vs_xla(client, qi):
    body = dict(QUERIES[qi], _probe=f"bool{qi}")
    noengage = body.pop("_noengage", False)
    fast, slow, engaged = _both(client, body)
    assert engaged or noengage, "bool fastpath did not engage"
    assert fast["hits"]["total"] == slow["hits"]["total"]
    assert _hits(fast) == _hits(slow)


def test_chunked_filtered(client):
    """Doc-range chunk decomposition with a filter slot riding along."""
    # T=4 slots (2 terms pow2 + filter): budget = MAX_TL//4 must stay above
    # the 1024-element DMA alignment slop per chunk
    old_l, old_tl = fastpath.MAX_L, fastpath.MAX_TL
    fastpath.MAX_L, fastpath.MAX_TL = 1 << 11, 1 << 13
    try:
        body = {"query": {"bool": {"must": [{"match": {"body": "common w23"}}],
                                   "filter": [FILTER_PUB]}},
                "size": 10, "_probe": "chunkbool"}
        fast, slow, engaged = _both(client, body)
        assert engaged
        assert fast["hits"]["total"] == slow["hits"]["total"]
        assert _hits(fast) == _hits(slow)
    finally:
        fastpath.MAX_L, fastpath.MAX_TL = old_l, old_tl


def test_filter_list_cached(client):
    """Repeated filters reuse one FilterList per segment."""
    b1 = {"query": {"bool": {"must": [{"term": {"body": "w2"}}],
                             "filter": [FILTER_PUB]}}, "size": 5,
          "_probe": "fc1"}
    b2 = {"query": {"bool": {"must": [{"term": {"body": "w9"}}],
                             "filter": [FILTER_PUB]}}, "size": 5,
          "_probe": "fc2"}
    client.search(index="bidx", body=b1)
    eng = client.node.indices["bidx"].shards[0]
    seg = eng.segments[0]
    n_before = len(getattr(seg, "_fastpath_filters", {}))
    assert n_before >= 1
    client.search(index="bidx", body=b2)
    assert len(seg._fastpath_filters) == n_before


def test_dense_filter_materializes(client):
    """A dense, repeated filter flips to filter-specialized postings and
    stays hit/score-identical to the XLA path."""
    old_min, old_den = (fastpath._MATERIALIZE_MIN_DOCS,
                        fastpath._MATERIALIZE_DENSITY)
    fastpath._MATERIALIZE_MIN_DOCS = 16
    fastpath._MATERIALIZE_DENSITY = 1000   # any filter counts as dense
    # drop FilterLists cached under the default thresholds (they didn't
    # retain their dense masks, so they can never take the new route)
    for eng in client.node.indices["bidx"].shards:
        for seg in eng.segments:
            getattr(seg, "_fastpath_filters", {}).clear()
    n0 = len(fastpath._FILTERED_LRU)
    try:
        body = {"query": {"bool": {"must": [{"match": {"body": "w2 w6"}}],
                                   "filter": [FILTER_PUB]}}, "size": 10}
        # first use: list path (hits=0); second: materializes
        for rep in range(3):
            fast, slow, engaged = _both(client, dict(body, _p=f"mat{rep}"))
            assert engaged
            assert fast["hits"]["total"] == slow["hits"]["total"]
            assert _hits(fast) == _hits(slow)
        assert len(fastpath._FILTERED_LRU) > n0, "did not materialize"
        # bonus-only shoulds under the same dense filter must NOT take the
        # specialized route (hits = whole filter, incl. docs w/o any term)
        bb = {"query": {"bool": {"should": [{"term": {"body": "w2"}}],
                                 "filter": [FILTER_PUB]}}, "size": 10}
        for rep in range(3):
            fast, slow, engaged = _both(client, dict(bb, _p=f"bmat{rep}"))
            assert engaged
            assert fast["hits"]["total"] == slow["hits"]["total"]
            assert _hits(fast) == _hits(slow)
    finally:
        fastpath._MATERIALIZE_MIN_DOCS = old_min
        fastpath._MATERIALIZE_DENSITY = old_den


def test_msearch_mixed_batch(client):
    """Batched msearch fuses pure and bool bodies into grouped launches."""
    bodies = [
        {"query": {"match": {"body": "w1 w2"}}, "size": 5},
        {"query": {"bool": {"must": [{"match": {"body": "w3 w4"}}],
                            "filter": [FILTER_PUB]}}, "size": 5},
        {"query": {"bool": {"filter": [FILTER_PRICE]}}, "size": 5},
    ]
    lines = []
    for b in bodies:
        lines.append({"index": "bidx"})
        lines.append(b)
    fastpath.set_enabled(True)
    fast = client.msearch(lines)
    fastpath.set_enabled(False)
    slow = client.msearch(lines)
    fastpath.set_enabled(True)
    for fr, sr in zip(fast["responses"], slow["responses"]):
        assert fr["hits"]["total"] == sr["hits"]["total"]
        assert _hits(fr) == _hits(sr)
