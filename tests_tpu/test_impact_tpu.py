"""TPU-hardware checks for the codec-v2 impact-gather kernel
(ops/pallas_bm25.fused_bm25_topk_impact): on a real chip the kernel's
quantized partial scores must reproduce the host mirror (weight × raw
quantized impact, one f32 multiply per posting) bit-for-bit, and the
block-compacted DMA windows must never leak skipped-block postings into
the result. Run on a real chip:
`python -m pytest tests_tpu/test_impact_tpu.py -q`."""

import numpy as np
import pytest

import jax

from opensearch_tpu.ops.pallas_bm25 import (HBM_ALIGN, INT_SENTINEL, LANES,
                                            align_csr_rows,
                                            fused_bm25_topk_impact)

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


def _host_mirror(docs_l, imps_l, weights, msm, k):
    """Exact host mirror of the kernel: per-doc sum of w·q over the
    supplied (doc, q) postings, msm-filtered, (score desc, doc asc)."""
    acc = {}
    cnt = {}
    for t, (ids, qs) in enumerate(zip(docs_l, imps_l)):
        for d, qv in zip(ids, qs):
            acc[d] = np.float32(acc.get(d, np.float32(0.0))
                                + np.float32(weights[t])
                                * np.float32(qv))
            cnt[d] = cnt.get(d, 0) + 1
    hits = [(d, s) for d, s in acc.items() if cnt[d] >= msm]
    hits.sort(key=lambda x: (-x[1], x[0]))
    return hits[:k]


@pytest.mark.parametrize("seed", [3, 11])
def test_impact_kernel_matches_host_mirror(seed):
    rng = np.random.default_rng(seed)
    nterms, ndocs = 4, 30_000
    starts_l = [0]
    docs_l, imps_l = [], []
    for _ in range(nterms):
        df = int(rng.integers(100, 5000))
        ids = np.sort(rng.choice(ndocs, size=df, replace=False))
        q = rng.integers(1, 65536, df)
        docs_l.append(ids.astype(np.int32))
        imps_l.append(q.astype(np.int32))
        starts_l.append(starts_l[-1] + df)
    starts = np.asarray(starts_l, np.int64)
    a_starts, a_docs, a_imp = align_csr_rows(
        starts, np.concatenate(docs_l), np.concatenate(imps_l),
        margin=1 << 16, alignment=LANES)

    T = 4
    K = 128
    weights = rng.uniform(0.1, 4.0, nterms).astype(np.float32)
    rowstarts = np.zeros((1, T), np.int32)
    nrows = np.zeros((1, T), np.int32)
    lens = np.zeros((1, T), np.int32)
    skips = np.zeros((1, T), np.int32)
    L = 1 << 13
    for t in range(nterms):
        abs_el = int(a_starts[t])
        dma_el = (abs_el // HBM_ALIGN) * HBM_ALIGN
        skip = abs_el - dma_el
        ln = int(starts[t + 1] - starts[t])
        rowstarts[0, t] = dma_el // LANES
        nr = 8
        while nr * LANES < skip + ln:
            nr *= 2
        nrows[0, t] = nr
        lens[0, t] = ln
        skips[0, t] = skip
        L = max(L, nr * LANES)
    w = weights[None, :]
    msm = np.array([[1.0]], np.float32)
    dlo = np.array([[0]], np.int32)
    dhi = np.array([[2**31 - 1]], np.int32)
    scores, out_docs, totals = jax.device_get(fused_bm25_topk_impact(
        jax.device_put(a_docs), jax.device_put(a_imp),
        rowstarts, nrows, lens, skips, w, msm, dlo, dhi,
        T=T, L=int(L), K=K))
    exp = _host_mirror(docs_l, imps_l, weights, 1, K)
    got = [(int(d), np.float32(s)) for s, d in zip(scores[0], out_docs[0])
           if d >= 0]
    assert len(got) == min(K, len(exp))
    for (gd, gs), (ed, es) in zip(got, exp):
        assert gd == ed
        assert gs == np.float32(es)    # bit-exact f32


def test_block_compacted_windows_exclude_skipped_postings():
    """Windows covering only a prefix of a row (the host block prune's
    compacted form) must score exactly that prefix."""
    ids = np.arange(0, 4096, 2, dtype=np.int32)      # 2048 postings
    q = np.full(2048, 100, np.int32)
    starts = np.asarray([0, 2048], np.int64)
    a_starts, a_docs, a_imp = align_csr_rows(
        starts, ids, q, margin=1 << 16, alignment=LANES)
    keep = 1024                                      # first 8 blocks only
    rowstarts = np.array([[int(a_starts[0]) // LANES]], np.int32)
    nrows = np.array([[8]], np.int32)
    lens = np.array([[keep]], np.int32)
    skips = np.array([[0]], np.int32)
    w = np.array([[2.0]], np.float32)
    msm = np.array([[1.0]], np.float32)
    dlo = np.array([[0]], np.int32)
    dhi = np.array([[2**31 - 1]], np.int32)
    scores, out_docs, totals = jax.device_get(fused_bm25_topk_impact(
        jax.device_put(a_docs), jax.device_put(a_imp),
        rowstarts, nrows, lens, skips, w, msm, dlo, dhi,
        T=1, L=1024, K=128))
    assert int(totals[0][0]) == keep
    assert int(out_docs[0].max()) < 2 * keep         # no skipped docs
    assert np.all(scores[0][:128] == np.float32(200.0))
