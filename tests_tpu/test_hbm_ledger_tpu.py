"""TPU-hardware checks for the HBM ledger (obs/hbm_ledger.py): on a real
chip the device allocator exposes `memory_stats()`, so the ledger's
silicon cross-check must run, stay inside the drift threshold for a
modest resident set, and report attributed residency that actually
landed in HBM. Run on a real chip:
`python -m pytest tests_tpu/test_hbm_ledger_tpu.py -q`."""

import jax
import pytest

from opensearch_tpu.cluster.node import Node
from opensearch_tpu.obs.hbm_ledger import LEDGER
from opensearch_tpu.rest.client import RestClient

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


def test_check_device_runs_and_holds():
    c = RestClient(node=Node(mesh_service=False))
    c.indices.create("hbmtpu", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 0},
        "mappings": {"properties": {"body": {"type": "text"}}}})
    for i in range(512):
        c.index("hbmtpu", {"body": f"alpha beta w{i % 37}"}, id=str(i))
    c.indices.refresh("hbmtpu")
    c.search("hbmtpu", {"query": {"match": {"body": "alpha"}}})

    check = LEDGER.check_device()
    assert check is not None, "TPU backend must expose memory_stats"
    assert check["bytes_in_use"] > 0
    assert check["ledger_bytes"] > 0
    # a fresh node with one small index must sit inside the modeled
    # threshold (XLA scratch/programs ride the 64 MiB floor)
    assert check["ok"], check

    hbm = c.nodes_stats()["nodes"]["node-0"]["hbm"]
    assert "device_check" in hbm
    assert hbm["tenants"].get("segment_columns", {}).get("bytes", 0) > 0 \
        or hbm["tenants"].get("aligned_postings", {}).get("bytes", 0) > 0
