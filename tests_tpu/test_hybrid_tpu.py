"""TPU-hardware checks for the hybrid retrieval engine (ISSUE 15):
on a real chip (1) the fused hybrid page must equal the pure host-side
fusion of its independently-served sub-pages (the coordinator contract
— fusion is a deterministic function of ranked device retrievals),
(2) the coalesced batched-knn route must serve BYTE-identical pages to
the direct per-body path (the f32 single-domain serving contract), and
(3) the balanced-IVF probe must hold recall against the exact
brute-force scan at device-native sizes. Run on a real chip:
`python -m pytest tests_tpu/test_hybrid_tpu.py -q`."""

import json
import random

import pytest

import jax

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")

DIMS = 128
NDOCS = 20_000


def _client(method=None):
    from opensearch_tpu.cluster.node import Node
    from opensearch_tpu.rest.client import RestClient

    vec = {"type": "dense_vector", "dims": DIMS, "similarity": "cosine"}
    if method is not None:
        vec["method"] = method
    c = RestClient(node=Node())
    c.indices.create("htpu", {
        "settings": {"number_of_shards": 1, "number_of_replicas": 0},
        "mappings": {"properties": {
            "body": {"type": "text"},
            "emb": {"type": "rank_features", "index_impacts": True},
            "vec": vec}}})
    rng = random.Random(17)
    vocab = [f"w{i}" for i in range(200)]
    feats = [f"t{i}" for i in range(60)]
    bulk = []
    for i in range(NDOCS):
        bulk.append({"index": {"_index": "htpu", "_id": str(i)}})
        bulk.append({
            "body": " ".join(rng.sample(vocab, 8)),
            "emb": {f: round(rng.expovariate(1.0) + 0.05, 3)
                    for f in rng.sample(feats, 6)},
            "vec": [rng.gauss(0, 1) for _ in range(DIMS)]})
    c.bulk(bulk)
    c.indices.refresh("htpu")
    return c, rng


def _hits(r):
    return [(h["_id"], h["_score"]) for h in r["hits"]["hits"]]


def test_fused_page_equals_host_fusion_of_device_subpages():
    from opensearch_tpu.search import fusion

    c, rng = _client()
    subs = [{"match": {"body": "w1 w2 w3"}},
            {"neural_sparse": {"emb": {"query_tokens": {
                "t1": 2.0, "t2": 1.0, "t9": 0.3}}}},
            {"knn": {"vec": {"vector": [rng.gauss(0, 1)
                                        for _ in range(DIMS)],
                             "k": 30}}}]
    spec = {"method": "rrf", "rank_constant": 25, "window_size": 40}
    got = c.search("htpu", {"query": {"hybrid": {
        "queries": subs, "fusion": spec}}, "size": 10})
    lists = []
    for sub in subs:
        r = c.search("htpu", {"query": sub, "size": 40})
        lists.append([((h["_index"], h["_id"]), h["_score"])
                      for h in r["hits"]["hits"]])
    fused = fusion.fuse_ranked_lists(lists, {
        "method": "rrf", "rank_constant": 25.0,
        "weights": [1.0, 1.0, 1.0], "normalization": "min_max"})
    assert [h for h, _ in _hits(got)] \
        == [key[1] for (key, _s) in fused[:10]]


def test_batched_knn_byte_identical_to_direct_on_device():
    from opensearch_tpu.search.executor import (msearch_batched,
                                                search_shards)

    c, rng = _client()
    searchers = c.node.indices["htpu"].searchers
    bodies = [{"query": {"knn": {"vec": {
        "vector": [rng.gauss(0, 1) for _ in range(DIMS)], "k": 10}}},
        "size": 10} for _ in range(8)]
    rs = msearch_batched(searchers, bodies, "htpu")
    assert rs is not None and all(r is not None for r in rs)
    for got, body in zip(rs, bodies):
        want = search_shards(searchers, dict(body), "htpu")
        assert json.dumps(_hits(got)) == json.dumps(_hits(want))
        assert got["hits"]["total"] == want["hits"]["total"]


def test_ivf_probe_recall_on_device():
    c, rng = _client(method={"name": "ivf",
                             "parameters": {"nlist": 64, "nprobe": 16}})
    hits = 0
    total = 0
    for _ in range(20):
        v = [rng.gauss(0, 1) for _ in range(DIMS)]
        approx = c.search("htpu", {"query": {"knn": {"vec": {
            "vector": v, "k": 10}}}, "size": 10})
        exact = c.search("htpu", {"query": {"knn": {"vec": {
            "vector": v, "k": 10, "exact": True}}}, "size": 10})
        a = {h["_id"] for h in approx["hits"]["hits"]}
        e = {h["_id"] for h in exact["hits"]["hits"]}
        hits += len(a & e)
        total += len(e)
    assert total > 0
    # balanced-IVF at nprobe=16/64 on random gaussians: the committed
    # recall floor (tests/test_ann.py pins the host-side equivalent)
    assert hits / total >= 0.6


def test_sparse_impact_ladder_serves_on_device():
    from opensearch_tpu.search import impactpath

    c, _ = _client()
    before = dict(impactpath.STATS)
    r = c.search("htpu", {"query": {"neural_sparse": {"emb": {
        "query_tokens": {"t1": 3.0, "t2": 1.5, "t9": 0.2,
                         "t11": 0.1}}}}, "size": 10})
    after = dict(impactpath.STATS)
    assert len(r["hits"]["hits"]) == 10
    assert after["served"] > before["served"]
    assert after["blocks_skipped"] >= before["blocks_skipped"]
