"""TPU-hardware parity for impact-head pruning: the REAL Pallas kernel
streaming head prefixes must match the dense XLA path exactly (modulo the
documented gte-totals contract). Run on a real chip:
`python -m pytest tests_tpu/test_pruned_tpu.py -q`."""

import numpy as np
import pytest

import jax

from opensearch_tpu.rest.client import RestClient
from opensearch_tpu.search import fastpath

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="needs a real TPU chip")


@pytest.fixture(scope="module")
def client(request):
    # shrink L_HEAD so a 20k-doc corpus genuinely clamps (df(common) ~12k)
    orig = fastpath.L_HEAD
    fastpath.L_HEAD = 1024
    request.addfinalizer(lambda: setattr(fastpath, "L_HEAD", orig))
    rng = np.random.default_rng(2)
    words = [f"w{i}" for i in range(400)]
    c = RestClient()
    c.indices.create("pidx")
    bulk = []
    for i in range(20_000):
        parts = list(rng.choice(words, size=10))
        if rng.random() < 0.6:
            parts.extend(["common"] * int(rng.integers(1, 4)))
        if rng.random() < 0.3:
            parts.append("semi")
        bulk.append({"index": {"_index": "pidx", "_id": str(i)}})
        bulk.append({"body": " ".join(parts)})
    c.bulk(bulk)
    c.indices.refresh("pidx")
    c.indices.forcemerge("pidx")
    return c


@pytest.mark.parametrize("body", [
    {"query": {"match": {"body": "common"}}, "size": 10},
    {"query": {"match": {"body": "common w3"}}, "size": 10},
    {"query": {"match": {"body": "common semi"}}, "size": 10},
    {"query": {"match": {"body": {"query": "common semi",
                                  "operator": "and"}}}, "size": 10},
    {"query": {"match": {"body": "w1 w2"}}, "size": 10},   # unclamped
])
def test_pruned_kernel_matches_exact(client, body):
    c = client
    before = dict(fastpath.STATS)
    pruned = c.search(index="pidx", body=dict(body))
    served = fastpath.STATS["pure_served"] - before["pure_served"]
    assert served == 1, "kernel did not serve the pruned query"
    exact_body = dict(body, track_total_hits=True)
    exact = c.search(index="pidx", body=exact_body)
    p = [(h["_id"], round(h["_score"], 4)) for h in pruned["hits"]["hits"]]
    e = [(h["_id"], round(h["_score"], 4)) for h in exact["hits"]["hits"]]
    assert p == e, body
    if pruned["hits"]["total"]["relation"] == "eq":
        assert pruned["hits"]["total"] == exact["hits"]["total"]
    else:
        assert pruned["hits"]["total"]["value"] <= \
            exact["hits"]["total"]["value"]


def test_pruning_actually_engaged(client):
    # size=11 so the request cache can't serve the earlier identical query
    c = client
    before = dict(fastpath.STATS)
    c.search(index="pidx", body={"query": {"match": {"body": "common"}},
                                 "size": 11})
    # single clamped term with a quantized boundary tie: the tie witness
    # must SERVE (an escalate here would double-run every such query)
    assert fastpath.STATS["pruned_served"] > before["pruned_served"]


def test_shard_view_single_launch_on_tpu():
    """Multi-segment shard -> one real-kernel launch over the shard view,
    identical to the per-segment XLA reference."""
    rng = np.random.default_rng(4)
    words = [f"s{i}" for i in range(60)]
    c = RestClient()
    c.indices.create("svidx", {"settings": {"number_of_shards": 1,
                                            "number_of_replicas": 0}})
    for wave in range(3):
        for i in range(wave * 300, wave * 300 + 300):
            c.index("svidx", {"body": " ".join(rng.choice(words, 8))},
                    id=f"{i:05d}")
        c.indices.refresh("svidx")
    assert len(c.node.indices["svidx"].shards[0].segments) >= 2
    before = dict(fastpath.STATS)
    fast = c.search(index="svidx",
                    body={"query": {"match": {"body": "s1 s2"}},
                          "size": 10})
    assert fastpath.STATS["shard_view_served"] > \
        before["shard_view_served"]
    fastpath.set_enabled(False)
    try:
        slow = c.search(index="svidx",
                        body={"query": {"match": {"body": "s1 s2"}},
                              "size": 10, "_ref": 1})
    finally:
        fastpath.set_enabled(True)
    assert [(h["_id"], round(h["_score"], 4))
            for h in fast["hits"]["hits"]] == \
        [(h["_id"], round(h["_score"], 4)) for h in slow["hits"]["hits"]]
