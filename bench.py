"""Benchmark: BM25 match-query throughput on one TPU chip vs a vectorized CPU
baseline, on a synthetic MS-MARCO-shaped corpus (Zipf term distribution,
~56 tokens/doc — see BASELINE.json config 1).

The device path is the framework's flagship fused Pallas kernel
(ops/pallas_bm25.py: async-DMA CSR posting ranges -> bitonic merge of the
doc-sorted runs -> shift-add dedup -> iterative top-k), one grid step per
query. The CPU baseline is a *vectorized numpy* scorer over the same CSR
postings — a stronger baseline than Lucene's per-doc BulkScorer loop, so
`vs_baseline` understates the advantage vs the reference.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env: BENCH_NDOCS (default 2_000_000), BENCH_QUERIES (default 256).
"""

import json
import os
import time

import numpy as np


def build_corpus(ndocs: int, vocab: int = 200_000, avg_dl: int = 56, seed: int = 0):
    rng = np.random.default_rng(seed)
    dl = np.clip(rng.lognormal(np.log(avg_dl), 0.4, ndocs), 8, 256).astype(np.int64)
    total = int(dl.sum())
    doc_of_tok = np.repeat(np.arange(ndocs, dtype=np.int64), dl)
    terms = rng.zipf(1.15, total).astype(np.int64)
    terms = np.where(terms > vocab, rng.integers(1, vocab, total), terms) - 1
    keys = terms * ndocs + doc_of_tok
    uniq, counts = np.unique(keys, return_counts=True)
    term_arr = (uniq // ndocs).astype(np.int64)
    doc_ids = (uniq % ndocs).astype(np.int32)
    tfs = counts.astype(np.float32)
    df_per_term = np.bincount(term_arr, minlength=vocab)
    starts = np.zeros(vocab + 1, dtype=np.int64)
    np.cumsum(df_per_term, out=starts[1:])
    return starts, doc_ids, tfs, dl, df_per_term


def pick_queries(df_per_term, nq: int, seed: int = 1):
    """2-term queries from mid-frequency terms (selective, MS-MARCO-like)."""
    rng = np.random.default_rng(seed)
    order = np.argsort(-df_per_term)
    lo, hi = 100, 20_000
    pool = order[lo:hi]
    pool = pool[df_per_term[pool] > 0]
    return rng.choice(pool, size=(nq, 2), replace=True).astype(np.int32)


def main():
    ndocs = int(os.environ.get("BENCH_NDOCS", 2_000_000))
    nq = int(os.environ.get("BENCH_QUERIES", 256))
    k = 10

    t0 = time.time()
    starts, doc_ids, tfs, dl, df_per_term = build_corpus(ndocs)
    queries = pick_queries(df_per_term, nq)
    sum_dl = float(dl.sum())
    avgdl = sum_dl / ndocs
    n_total = float(ndocs)
    idf = np.log1p((n_total - df_per_term + 0.5) / (df_per_term + 0.5)).astype(np.float32)
    build_s = time.time() - t0

    # ---------------- CPU baseline (vectorized numpy) ----------------
    k1, b = 1.2, 0.75
    K_doc = (k1 * (1 - b + b * dl / avgdl)).astype(np.float32)

    def cpu_query(q):
        scores = np.zeros(ndocs, np.float32)
        for t in q:
            a, e = starts[t], starts[t + 1]
            d = doc_ids[a:e]
            tf = tfs[a:e]
            np.add.at(scores, d, idf[t] * tf / (tf + K_doc[d]))
        top = np.argpartition(scores, -k)[-k:]
        return top[np.argsort(-scores[top])]

    ncpu = min(nq, 64)
    t0 = time.time()
    cpu_results = [cpu_query(q) for q in queries[:ncpu]]
    cpu_s = time.time() - t0
    cpu_qps = ncpu / cpu_s

    # ---------------- TPU path: fused Pallas BM25 top-k kernel ----------------
    # (see opensearch_tpu/ops/pallas_bm25.py — DMA CSR ranges, bitonic-merge
    # the doc-sorted runs, shift-add dedup, iterative top-k; no XLA
    # gather/scatter/sort, which all serialize on TPU)
    import jax

    from opensearch_tpu.ops.pallas_bm25 import align_csr_rows, fused_bm25_topk

    dev = jax.devices()[0]
    # eager impacts (BM25S-style): tf/(tf + K_doc) precomputed at index time
    impacts = (tfs / (tfs + K_doc[doc_ids])).astype(np.float32)
    T, K = 2, k
    L = 1 << int(np.ceil(np.log2(max(int((starts[queries + 1] - starts[queries]).max()),
                                     1024))))
    a_starts, a_docs, a_imp = align_csr_rows(starts, doc_ids, impacts, margin=L)
    d_docs = jax.device_put(a_docs, dev)
    d_imp = jax.device_put(a_imp, dev)
    qs = jax.device_put(a_starts[queries].astype(np.int32), dev)
    ql = jax.device_put((starts[queries + 1] - starts[queries]).astype(np.int32), dev)
    qw = jax.device_put(idf[queries], dev)
    msm = jax.device_put(np.ones((nq, 1), np.float32), dev)

    # NOTE on timing: this chip sits behind a tunnel with ~70ms per
    # host<->device round trip. All queries are staged on device and scored
    # in ONE kernel launch (grid over queries) — the same shape a production
    # TPU search tier uses (server-side query batching).
    _ = np.asarray(fused_bm25_topk(d_docs, d_imp, qs, ql, qw, msm, T=T, L=L, K=K)[1])

    reps = 5
    t0 = time.time()
    for _ in range(reps):
        vals, idx, _tot = fused_bm25_topk(d_docs, d_imp, qs, ql, qw, msm, T=T, L=L, K=K)
    results_flat = np.asarray(idx)[:, :k]
    wall = time.time() - t0
    qps = (reps * nq) / wall
    batch_p50 = wall / reps

    # recall@10 parity vs CPU baseline on the overlap
    tpu_all = results_flat
    overlap = min(len(cpu_results), len(tpu_all))
    recall = np.mean([len(set(cpu_results[i]) & set(tpu_all[i])) / k
                      for i in range(overlap)])

    print(json.dumps({
        "metric": "bm25_qps_per_chip",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "vs_baseline": round(qps / cpu_qps, 2),
        "extra": {"ndocs": ndocs, "batch_ms_all_queries": round(batch_p50 * 1000, 2),
                  "cpu_qps": round(cpu_qps, 2),
                  "recall_at_10_vs_cpu": round(float(recall), 4),
                  "corpus_build_s": round(build_s, 1),
                  "postings": int(len(doc_ids)), "L": L},
    }))


if __name__ == "__main__":
    main()
